#!/usr/bin/env python
"""Cardinality storm driver: mint N unique tag values at a given rate.

Reproduces a tag-cardinality explosion against a dev server so the
observatory (`GET /debug/cardinality`), the `columnstore.*` capacity
telemetry, and the `cardinality_soft_limit` / `cardinality_hard_limit`
shed rung can be exercised end to end:

    # start a dev server with tight limits, then:
    python scripts/cardinality_storm.py \
        --hostport udp://127.0.0.1:8126 \
        --name storm.metric --tag-key user_id \
        --keys 100000 --pps 20000 --duration 30

    # watch it land:
    curl 'http://127.0.0.1:8127/debug/cardinality?name=storm.metric'
    curl -s http://127.0.0.1:8127/metrics | grep -E 'cardinality|shed'

Each packet is `<name>:1|<type>|#<tag-key>:v<i>` with `i` walking
0..keys-1 (wrapping, so a long storm keeps touching the same key set —
steady-state churn — while a short one is pure minting). `--spray`
additionally randomizes a second tag so every packet is a unique series
(the worst case: nothing ever re-interns).
"""

from __future__ import annotations

import argparse
import random
import socket
import sys
import time


def parse_hostport(hostport: str):
    scheme, rest = "udp", hostport
    if "://" in hostport:
        scheme, rest = hostport.split("://", 1)
    host, _, port = rest.rpartition(":")
    return scheme, host or "127.0.0.1", int(port)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="cardinality_storm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--hostport", default="udp://127.0.0.1:8126")
    ap.add_argument("--name", default="cardinality.storm",
                    help="metric name every minted key shares")
    ap.add_argument("--tag-key", default="storm_id",
                    help="the exploding tag key")
    ap.add_argument("--keys", type=int, default=10000,
                    help="distinct tag values to mint (wraps)")
    ap.add_argument("--pps", type=float, default=5000.0,
                    help="target packets/second")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="storm length in seconds")
    ap.add_argument("--type", default="c", choices=["c", "g", "ms", "s"],
                    help="metric type of the storm samples")
    ap.add_argument("--spray", action="store_true",
                    help="add a random second tag so EVERY packet is a "
                         "unique series (pure mint load, never wraps)")
    ap.add_argument("--extra-tag", action="append", default=[],
                    help="static tag(s) on every packet (k:v)")
    args = ap.parse_args(argv)

    scheme, host, port = parse_hostport(args.hostport)
    if scheme != "udp":
        print("storm mode supports udp only", file=sys.stderr)
        return 2
    static = ("," + ",".join(args.extra_tag)) if args.extra_tag else ""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rng = random.Random()

    sent = 0
    start = time.perf_counter()
    end = start + args.duration
    batch = max(1, int(args.pps // 100))  # pace in ~10ms slices
    try:
        while True:
            now = time.perf_counter()
            if now >= end:
                break
            if sent > (now - start) * args.pps:
                time.sleep(min(0.01, (sent - (now - start) * args.pps)
                               / max(args.pps, 1.0)))
                continue
            for _ in range(batch):
                i = sent % args.keys
                tags = f"{args.tag_key}:v{i}{static}"
                if args.spray:
                    tags += f",spray:{rng.getrandbits(48):x}"
                packet = f"{args.name}:1|{args.type}|#{tags}".encode()
                sock.sendto(packet, (host, port))
                sent += 1
    finally:
        sock.close()
    elapsed = time.perf_counter() - start
    minted = sent if args.spray else min(sent, args.keys)
    print(f"storm: sent {sent} packets at {sent / elapsed:.0f}/s "
          f"({minted} unique series minted, tag key {args.tag_key!r})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
