#!/usr/bin/env python
"""Ring-failover soak driver: kill/restore a global destination on a
timer while streaming metrics through the proxy, and account for every
metric.

What it exercises (the forward-tier HA machinery, PR "Forward-tier high
availability"):

- the proxy's active health probes eject the killed destination from
  the consistent-hash ring (its keys re-shard onto the survivors);
- routing failover keeps mergeable state flowing while the breaker of
  the dead node is open;
- readmission restores the original assignment when the node returns.

The invariant the soak pins is ACCOUNTING EXACTNESS, not zero loss: the
proxy tier is deliberately memoryless (lossless carryover/spool live on
the local tier), so metrics enqueued at a dying destination in the
detection window are dropped — but every one of them must be COUNTED
(`routed == received`, `sent == received + counted drops`), and once
ejection lands the stream must flow loss-free through the survivors.

Runnable standalone:

    JAX_PLATFORMS=cpu python scripts/ring_failover_soak.py \
        --rounds 12 --per-round 200 --kill-round 3 --restore-round 7

and from the `slow`/`ha`-marked soak test (tests/test_ha.py), which
drives `run_soak()` directly and asserts the report's invariants.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# standalone invocation from the repo root (the package need not be
# installed; same pattern as scripts/cardinality_storm.py)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def wait_until(pred, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def run_soak(rounds: int = 12, per_round: int = 200, n_dest: int = 3,
             kill_round: int = 3, restore_round: int = 7,
             probe_interval: float = 0.1, verbose: bool = False) -> dict:
    """Stream `rounds` batches of counters through a proxy over `n_dest`
    in-process global stubs, killing destination 0 at `kill_round` and
    restoring it (same port) at `restore_round`. Returns the accounting
    report; raises AssertionError when an invariant breaks."""
    from veneur_tpu.forward.client import ForwardClient
    from veneur_tpu.forward.protos import metric_pb2
    from veneur_tpu.proxy.proxy import create_static_proxy
    from veneur_tpu.testing.forwardtest import ForwardTestServer

    received = [[] for _ in range(n_dest)]
    servers = []
    for i in range(n_dest):
        servers.append(ForwardTestServer(received[i].extend))
        servers[i].start()
    addresses = [s.address for s in servers]

    proxy = create_static_proxy(
        addresses,
        # fast detection for the soak; production defaults are 2s/3/2
        health_check_interval=probe_interval,
        health_check_timeout=0.25,
        health_unhealthy_after=2,
        health_healthy_after=2)
    proxy.start()
    client = ForwardClient(proxy.address, deadline=5.0)

    def mk(name, value):
        pbm = metric_pb2.Metric(name=name, type=metric_pb2.Counter,
                                scope=metric_pb2.Global)
        pbm.counter.value = value
        return pbm

    sent = 0
    events = []
    post_eject_sent = 0
    try:
        for rnd in range(rounds):
            if rnd == kill_round:
                servers[0].stop()
                events.append({"round": rnd, "event": "killed",
                               "address": addresses[0]})
                # wait for the prober to eject it so the re-shard window
                # is deterministic in the report — rounds from here on
                # must be loss-free (asserted below)
                ejection_confirmed = wait_until(
                    lambda: addresses[0]
                    in proxy.destinations.ejected_addresses(),
                    timeout=10.0)
                events.append({"round": rnd, "event": "ejected",
                               "confirmed": ejection_confirmed})
            if rnd == restore_round:
                servers[0] = ForwardTestServer(received[0].extend,
                                               address=addresses[0])
                servers[0].start()
                events.append({"round": rnd, "event": "restored"})
                wait_until(lambda: addresses[0]
                           not in proxy.destinations.ejected_addresses(),
                           timeout=10.0)
                events.append({"round": rnd, "event": "readmitted"})
            batch = [mk(f"soak.m.{rnd}.{i}", 1) for i in range(per_round)]
            client.send_protos(batch)
            sent += per_round
            if addresses[0] in proxy.destinations.ejected_addresses() \
                    or rnd >= restore_round:
                post_eject_sent += per_round
            if verbose:
                print(f"round {rnd}: sent {per_round} "
                      f"(ejected={proxy.destinations.ejected_addresses()})",
                      file=sys.stderr)
        # settle: wait until the books balance — every sent metric is
        # either received by a global or counted as a drop (live or
        # retired destination) / no-destination at the proxy. The
        # retired_* fold matters: a destination that self-closed on an
        # open breaker was REPLACED by discovery, and its counters
        # would otherwise vanish from the pool.
        proxy.destinations.flush_wait(timeout=10.0)

        def drops_total():
            dests = proxy.destinations
            with dests._lock:
                live = sum(d.dropped_total for d in dests._pool.values())
                return live + dests.retired_dropped_total

        stats_settle = wait_until(
            lambda: sum(len(r) for r in received) + drops_total()
            + proxy.stats["no_destination_total"] >= sent,
            timeout=10.0)
    finally:
        client.close()
        proxy_stats = dict(proxy.stats)
        dest_rows = {d.address: {"sent": d.sent_total,
                                 "dropped": d.dropped_total,
                                 "shed_open": d.shed_open_total}
                     for d in proxy.destinations._pool.values()}
        dest_rows["<retired>"] = {
            "sent": proxy.destinations.retired_sent_total,
            "dropped": proxy.destinations.retired_dropped_total,
            "shed_open": proxy.destinations.retired_shed_open_total}
        health_rows = (proxy.ring_health.member_table()
                       if proxy.ring_health else [])
        proxy.stop()
        for s in servers:
            s.stop()

    got = sum(len(r) for r in received)
    dropped = sum(v["dropped"] for v in dest_rows.values())
    report = {
        "sent": sent,
        "received": got,
        "proxy": proxy_stats,
        "destinations": dest_rows,
        "member_table": health_rows,
        "events": events,
        "settled": stats_settle,
        # metrics lost in the kill->ejection detection window (the only
        # legitimate loss at the memoryless proxy tier)
        "detection_window_loss": sent - got,
    }
    accounted = (got + dropped + proxy_stats["no_destination_total"])
    report["accounted"] = accounted
    report["loss_unaccounted"] = sent - accounted
    assert proxy_stats["received_total"] == sent, report
    assert report["loss_unaccounted"] == 0, report
    # the loss-free-after-ejection invariant, asserted per metric: every
    # round sent at-or-after the CONFIRMED ejection (the dead member is
    # out of the ring before that round's batch goes in) must land —
    # drops are confined to the kill->ejection detection window
    report["post_eject_sent"] = post_eject_sent
    ejected_ok = any(e["event"] == "ejected" and e.get("confirmed")
                     for e in events)
    if ejected_ok and kill_round < rounds:
        received_names = {p.name for dest in received for p in dest}
        missing = [f"soak.m.{rnd}.{i}"
                   for rnd in range(kill_round, rounds)
                   for i in range(per_round)
                   if f"soak.m.{rnd}.{i}" not in received_names]
        report["post_eject_missing"] = len(missing)
        assert not missing, (missing[:10], report)
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--per-round", type=int, default=200)
    ap.add_argument("--destinations", type=int, default=3)
    ap.add_argument("--kill-round", type=int, default=3)
    ap.add_argument("--restore-round", type=int, default=7)
    ap.add_argument("--probe-interval", type=float, default=0.1)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    report = run_soak(rounds=args.rounds, per_round=args.per_round,
                      n_dest=args.destinations,
                      kill_round=args.kill_round,
                      restore_round=args.restore_round,
                      probe_interval=args.probe_interval,
                      verbose=args.verbose)
    json.dump(report, sys.stdout, indent=2, default=str)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
