#!/usr/bin/env python
"""Crash/replay soak driver: SIGKILL a local mid-flush, restart it,
replay the WAL, and diff the global's final state against an unfaulted
control.

What it exercises (the durable interval WAL, "Durable interval WAL &
timestamp-faithful backfill replay"):

- `forward_wal: true` appends every forwardable interval snapshot to
  disk (fsync'd, interval-stamped) BEFORE the send attempt;
- a `kill -9` landing between the append and the receiver's ack loses
  nothing: the restarted process re-scans the spool and replays the
  unacked interval;
- per-segment idempotency tokens (derived from the on-disk name,
  stable across restarts) make the replay exactly-once — a segment
  whose send landed but whose ack was lost is deduped, not re-merged.

The kill is made deterministic the honest way: the child local runs
with `chaos_forward_latency_ms` high enough that every forward send
hangs mid-flight, the driver waits until a fresh WAL segment appears on
disk (the append happened; the flush is mid-send), and THEN delivers
SIGKILL. The restarted child runs with chaos off and drains the log.

The invariant pinned is EXACTNESS, not accounting: after N kill/restart
rounds the faulted pipeline's global must hold the same counter sums as
an unfaulted control fed the identical stream, and the llhist family's
registers must match BIT FOR BIT (register-add merges are exact
regardless of arrival order — the Circllhist property the WAL's replay
correctness rests on).

Runnable standalone:

    JAX_PLATFORMS=cpu python scripts/crash_replay_soak.py \
        --kills 3 --counters-per-round 40 --value 3

and from the `wal`+`slow`-marked soak test (tests/test_wal.py), which
drives `run_soak()` directly and asserts the report's invariants.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CHILD_ENV_FLAG = "CRASH_REPLAY_SOAK_CHILD"


def wait_until(pred, timeout=30.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


# ---------------------------------------------------------------------------
# child: one local server, WAL on, forwarding to the parent's global
# ---------------------------------------------------------------------------


def run_child() -> None:
    """Child-process entry: a real local Server with the WAL enabled,
    reading DogStatsD lines from stdin ("feed" protocol: one line per
    metric packet, `FLUSH\\n` triggers a flush, EOF exits after a final
    flush). Forward sends hang for CHAOS_MS, so the parent can SIGKILL
    this process provably mid-flight."""
    from veneur_tpu.config import Config
    from veneur_tpu.core.server import Server

    cfg = Config()
    cfg.interval = 3600.0  # flushes are driven by the feed protocol
    cfg.hostname = "soak-local"
    cfg.forward_address = os.environ["SOAK_FORWARD_ADDRESS"]
    cfg.carryover_spool_dir = os.environ["SOAK_WAL_DIR"]
    cfg.forward_wal = True
    cfg.forward_retry_max_attempts = 1
    cfg.circuit_breaker_failure_threshold = 10_000
    # acceptance pin: every interval's books must close with zero
    # unexplained imbalance THROUGH the kill/replay cycle — strict
    # raises out of flush(), so "FLUSHED" never prints and the soak
    # fails loudly
    cfg.ledger_strict = True
    cfg.jax_compilation_cache_dir = os.environ.get("SOAK_COMPILE_CACHE", "")
    chaos_ms = float(os.environ.get("SOAK_CHAOS_MS", "0"))
    if chaos_ms:
        cfg.chaos_enabled = True
        cfg.chaos_forward_latency_ms = chaos_ms
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    cfg.apply_defaults()
    server = Server(cfg)
    server.start()
    print("READY", flush=True)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        if line == "FLUSH":
            server.flush()
            print("FLUSHED", flush=True)
            continue
        server.handle_metric_packet(line.encode())
    server.store.apply_all_pending()
    server.flush()
    print("DONE", flush=True)


# ---------------------------------------------------------------------------
# parent: two in-process globals (faulted path + control), the kill loop
# ---------------------------------------------------------------------------


def _mk_global():
    from veneur_tpu.config import Config
    from veneur_tpu.core.server import Server
    from veneur_tpu.forward.server import ImportServer
    from veneur_tpu.sinks.channel import ChannelMetricSink

    cfg = Config()
    cfg.interval = 3600.0
    cfg.hostname = "soak-global"
    cfg.statsd_listen_addresses = []
    cfg.ledger_strict = True
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    server = Server(cfg, extra_metric_sinks=[obs])
    imp = ImportServer(server, "127.0.0.1:0")
    imp.start()
    return server, imp, obs


def _spawn_child(wal_dir: str, forward_address: str, chaos_ms: float,
                 compile_cache: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        CHILD_ENV_FLAG: "1",
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        "SOAK_FORWARD_ADDRESS": forward_address,
        "SOAK_WAL_DIR": wal_dir,
        "SOAK_CHAOS_MS": str(chaos_ms),
        "SOAK_COMPILE_CACHE": compile_cache,
    })
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        env=env, text=True, bufsize=1)
    assert wait_until(lambda: proc.stdout.readline().strip() == "READY",
                      timeout=120.0), "child never came up"
    return proc


def _feed(proc: subprocess.Popen, lines) -> None:
    for line in lines:
        proc.stdin.write(line + "\n")
    proc.stdin.flush()


def _wal_segments(wal_dir: str):
    try:
        return sorted(f for f in os.listdir(wal_dir)
                      if f.endswith(".vspool"))
    except OSError:
        return []


def run_soak(kills: int = 3, counters_per_round: int = 40,
             value: int = 3, chaos_ms: float = 20_000.0,
             verbose: bool = False) -> dict:
    """`kills` rounds of feed -> flush -> SIGKILL-mid-send -> restart ->
    replay, then a clean final round. Returns the comparison report;
    raises AssertionError when an invariant breaks."""
    import numpy as np

    faulted, f_imp, _ = _mk_global()
    control, c_imp, _ = _mk_global()
    tmp = tempfile.mkdtemp(prefix="crash-replay-soak-")
    wal_dir = os.path.join(tmp, "wal")
    ctl_wal_dir = os.path.join(tmp, "wal-control")
    cache_dir = os.path.join(tmp, "compile-cache")
    report = {"kills": 0, "restarts": 0, "rounds": []}

    def lines_for(round_no: int):
        # counters ride the magic global-scope tag so a LOCAL forwards
        # them (mixed-scope counters flush locally); llhist samples are
        # mixed-scope and forward their registers by default
        out = []
        for i in range(counters_per_round):
            out.append(f"soak.cnt.{i % 8}:{value}|c"
                       f"|#veneurglobalonly")
            out.append(f"soak.llh.{i % 4}:{(round_no * 17 + i) % 91}|l")
        return out

    child = None
    ctl = _spawn_child(ctl_wal_dir, c_imp.address, 0.0, "")
    try:
        for round_no in range(kills):
            if child is not None:
                # the previous round's replay child ran chaos-free (its
                # WAL is drained); each kill round needs the hang seam
                # back, so respawn with chaos on
                child.kill()
                child.wait()
            child = _spawn_child(wal_dir, f_imp.address, chaos_ms,
                                 cache_dir)
            lines = lines_for(round_no)
            _feed(child, lines)
            _feed(ctl, lines + ["FLUSH"])
            assert wait_until(
                lambda: ctl.stdout.readline().strip() == "FLUSHED",
                timeout=60.0)
            before = set(_wal_segments(wal_dir))
            _feed(child, ["FLUSH"])
            # the WAL append lands BEFORE the (chaos-delayed) send:
            # the moment a fresh segment is on disk the flush is
            # provably mid-send — kill -9 now
            assert wait_until(
                lambda: set(_wal_segments(wal_dir)) - before,
                timeout=60.0), "WAL segment never appeared pre-ack"
            child.kill()
            child.wait()
            report["kills"] += 1
            # restart with chaos OFF: the re-scan replays the log
            child = _spawn_child(wal_dir, f_imp.address, 0.0, cache_dir)
            report["restarts"] += 1
            _feed(child, ["FLUSH"])  # drains the replayed segments
            assert wait_until(
                lambda: child.stdout.readline().strip() == "FLUSHED",
                timeout=60.0)
            assert wait_until(lambda: not _wal_segments(wal_dir),
                              timeout=30.0), "WAL did not drain"
            if verbose:
                print(f"round {round_no}: killed + replayed")
            report["rounds"].append(round_no)
        # clean final round on both pipelines
        lines = lines_for(kills)
        _feed(child, lines + ["FLUSH"])
        assert wait_until(
            lambda: child.stdout.readline().strip() == "FLUSHED",
            timeout=60.0)
        _feed(ctl, lines + ["FLUSH"])
        assert wait_until(
            lambda: ctl.stdout.readline().strip() == "FLUSHED",
            timeout=60.0)
    finally:
        for proc in (child, ctl):
            try:
                proc.kill()
            except OSError:
                pass

    # -- the diff: zero counter loss, llhist registers bit-identical ----
    def counter_sums(server):
        table = server.store.counters
        server.store.apply_all_pending()
        vals, touched, meta = table.snapshot_and_reset()
        out = {}
        for row in np.flatnonzero(np.asarray(touched)).tolist():
            if meta[row] is not None:
                out[meta[row].name] = float(np.asarray(vals)[row])
        return out

    def llhist_bins(server):
        table = server.store.llhists
        ps = (0.5,)
        _out, bins, touched, meta = table.snapshot_and_reset(ps)
        out = {}
        for i, row in enumerate(np.flatnonzero(np.asarray(touched)).tolist()):
            if meta[row] is not None:
                out[meta[row].name] = np.asarray(bins)[i]
        return out

    f_counters = counter_sums(faulted)
    c_counters = counter_sums(control)
    assert f_counters == c_counters, (
        f"counter loss: faulted {f_counters} != control {c_counters}")
    f_bins = llhist_bins(faulted)
    c_bins = llhist_bins(control)
    assert set(f_bins) == set(c_bins), (set(f_bins), set(c_bins))
    for name in f_bins:
        assert np.array_equal(f_bins[name], c_bins[name]), (
            f"llhist registers diverge for {name}")
    # conservation: zero unexplained imbalance on the receiving tier
    faulted.ledger.close_interval()
    control.ledger.close_interval()
    report["counters"] = f_counters
    report["llhist_names"] = sorted(f_bins)
    report["dedupe_drops"] = f_imp.duplicates_dropped_total
    f_imp.stop()
    c_imp.stop()
    return report


def main(argv=None) -> int:
    if os.environ.get(CHILD_ENV_FLAG):
        run_child()
        return 0
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--kills", type=int, default=3)
    ap.add_argument("--counters-per-round", type=int, default=40)
    ap.add_argument("--value", type=int, default=3)
    ap.add_argument("--chaos-ms", type=float, default=20_000.0)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    report = run_soak(kills=args.kills,
                      counters_per_round=args.counters_per_round,
                      value=args.value, chaos_ms=args.chaos_ms,
                      verbose=args.verbose)
    print(json.dumps(report, indent=2, default=str))
    print(f"ok: {report['kills']} kill(s), {report['restarts']} "
          f"restart(s), zero loss, llhist bit-exact")
    return 0


if __name__ == "__main__":
    sys.exit(main())
