#!/usr/bin/env python
"""Per-kernel microbenchmark: one JSON object with the hot-op timings
that explain the pipeline numbers (the round-3 manual artifact carried
an ad-hoc version of this table; this makes it reproducible).

Covers the device kernels (t-digest apply/compact/flush-export, HLL
apply/estimate — reference analogs tdigest/merging_digest.go Add/
Compress/Quantile and vendor axiomhq hyperloglog Estimate), the Pallas
vs XLA flush A/B at the 100k-key production shape, and the native
forward-plane encoder (reference analog: flusher.go:578-591's implicit
Go protobuf serialization).

Usage: python scripts/kernel_microbench.py [--keys 100000] [--out PATH]
Runs on whatever backend initializes (TPU when the tunnel is up; the
platform lands in the JSON either way). Safe under a wedged tunnel:
probe the backend with bench.initialize_backend first when run via
scripts/: it falls back to CPU with provenance instead of hanging.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _emit(out: dict, path: str | None) -> None:
    """Emit the JSON record exactly once (the success path and the
    deadline timer race to call this). dict(out) snapshots under the
    GIL before json.dumps walks it, so a concurrent key assignment in
    the other thread can't blow up the serialization."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return
        _EMITTED = True
        line = json.dumps(dict(out))
        print(line, flush=True)
        if path:
            with open(path, "w") as f:
                f.write(line + "\n")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=100_000)
    ap.add_argument("--batch", type=int, default=16_384)
    ap.add_argument("--out", default=None,
                    help="also write the JSON object to this path")
    args = ap.parse_args()

    import bench  # repo-root harness: backend probe + timing helpers

    out = {}
    # own deadline guard (NOT bench.arm_deadline: its expiry path emits
    # the pipeline-schema JSON line, which is the wrong schema here and
    # would discard the kernel timings already collected) — `out` fills
    # incrementally, so expiry flushes a truncated-but-real record
    deadline = float(os.environ.get("BENCH_DEADLINE_S", 600))

    def _expire():
        out["truncated"] = f"deadline ({deadline:.0f}s) reached"
        _emit(out, args.out)
        os._exit(3)

    timer = threading.Timer(deadline, _expire)
    timer.daemon = True
    timer.start()

    median_time = bench._time_flush  # one timing methodology for both
    platform = bench.initialize_backend()
    import jax
    import numpy as np

    from veneur_tpu.ops import batch_hll, batch_tdigest, scalars

    K, B = args.keys, args.batch
    rng = np.random.default_rng(11)
    out.update(platform=platform, keys=K, batch=B)

    # ---- t-digest ----
    state = batch_tdigest.init_state(K)
    rows = rng.integers(0, K, B).astype(np.int32)
    vals = rng.normal(100, 15, B).astype(np.float32)
    wts = np.ones(B, np.float32)
    slots = batch_tdigest.host_ranks(rows)
    dev = jax.device_put((rows, vals, wts, slots))
    apply_j = jax.jit(batch_tdigest.apply_batch)
    state = apply_j(state, *dev)  # populate + compile
    out["tdigest_apply_ms_per_batch"] = round(
        median_time(lambda: apply_j(state, *dev)) * 1e3, 3)

    compact_j = jax.jit(batch_tdigest.compact)
    state = compact_j(state)
    out["tdigest_compact_ms"] = round(
        median_time(lambda: compact_j(state)) * 1e3, 2)

    ps = (0.5, 0.9, 0.99)
    # shared A/B policy (trim/gate/fairness) — bench.measure_flush_ab is
    # the single definition; convert its seconds to this table's ms
    for k, v in bench.measure_flush_ab(state, K, ps).items():
        out[k.replace("_s", "_ms") if k.endswith("_s") else k] = (
            round(v * 1e3, 2) if isinstance(v, float) else v)

    # ---- HLL ----
    hk = max(1, K // 8)
    regs = batch_hll.init_state(hk)
    s_rows = rng.integers(0, hk, B).astype(np.int32)
    s_idx = rng.integers(0, batch_hll.M, B).astype(np.int32)
    s_rho = rng.integers(1, 30, B).astype(np.int32)
    sdev = jax.device_put((s_rows, s_idx, s_rho))
    happly_j = jax.jit(batch_hll.apply_batch)
    regs = happly_j(regs, *sdev)
    out["hll_apply_ms_per_batch"] = round(
        median_time(lambda: happly_j(regs, *sdev)) * 1e3, 3)
    out["hll_keys"] = hk
    out["hll_estimate_ms"] = round(
        median_time(lambda: batch_hll.estimate(regs)) * 1e3, 2)

    # ---- scalar families ----
    counters = scalars.init_counters(K)
    c_rows = rng.integers(0, K, B).astype(np.int32)
    c_vals = (rng.random(B) * 10).astype(np.float32)
    c_rates = np.ones(B, np.float32)
    cdev = jax.device_put((c_rows, c_vals, c_rates))
    capply_j = jax.jit(scalars.apply_counters)
    counters = capply_j(counters, *cdev)
    out["counter_apply_ms_per_batch"] = round(
        median_time(lambda: capply_j(counters, *cdev)) * 1e3, 3)

    # ---- native forward-plane encoder (host-side, no device) ----
    try:
        from veneur_tpu.core.columnstore import MetricScope, RowMeta
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward import convert as cv
        from veneur_tpu.forward.convert import forwardable_to_wire

        FK, C = 50_000, 128
        metas = [RowMeta(name=f"mb.fwd.{i}", tags=[f"h:{i % 100}"],
                         joined_tags=f"h:{i % 100}", digest32=i,
                         scope=MetricScope.MIXED,
                         wire_type=cv.m.TIMER)
                 for i in range(FK)]
        means = rng.normal(100, 15, (FK, C)).astype(np.float32)
        weights = rng.uniform(0, 50, (FK, C)).astype(np.float32)
        weights[:, C // 2:] = 0
        fwd = ForwardableState(histograms=[
            (metas[i], means[i], weights[i], 1.0, 200.0, 0.5)
            for i in range(FK)])
        forwardable_to_wire(fwd)  # warm the per-meta frame caches
        t0 = time.perf_counter()
        wire = forwardable_to_wire(fwd)
        dt = time.perf_counter() - t0
        out["forward_encode_keys_per_s"] = round(FK / dt, 1)
        out["forward_encode_keys"] = FK
        out["forward_wire_mb"] = round(sum(len(w) for w in wire) / 1e6, 1)
    except Exception as e:
        out["forward_encode_error"] = f"{type(e).__name__}: {e}"

    _emit(out, args.out)
    return 0


if __name__ == "__main__":
    rc = main()
    sys.stdout.flush()
    os._exit(rc)
