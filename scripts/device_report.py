#!/usr/bin/env python
"""Pretty-print a device capacity & shard-balance report.

Reads ``GET /debug/device`` from a live veneur-tpu server — or a saved
JSON file — and renders the device observatory as text: the HBM
generation ledger (per family, per lifecycle state, with the
next-resize forecast and backend reconciliation where the runtime
exposes allocator stats), the kernel dispatch/compile registry, the
per-shard balance picture with the skew ratio and any recommended
reshard plan, and the overload ladder's device watermark rung.

Usage:
    python scripts/device_report.py http://127.0.0.1:8127/debug/device
    python scripts/device_report.py http://host:8127
    python scripts/device_report.py saved-device.json
    python scripts/device_report.py http://host:8127 --skew-threshold 2

Exit codes: 0 = healthy, 1 = ledger occupancy at/over the hard device
watermark OR shard skew at/over the alert threshold, 2 = could not
read input.

stdlib-only (urllib) so it runs anywhere the operator has Python.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

# the hot-shard bar deviceobs uses; --skew-threshold overrides
DEFAULT_SKEW_THRESHOLD = 2.0

_STATE_ORDER = ("live", "spare", "inflight", "prewarm", "reshard_capture")


def _mb(v) -> str:
    if v is None:
        return "-"
    return f"{float(v) / (1 << 20):.2f}MiB"


def load_report(source: str) -> dict:
    """Fetch the report from a URL (``/debug/device`` appended when the
    path is missing) or read it from a JSON file."""
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen
        url = source
        if "/debug/device" not in url:
            url = url.rstrip("/") + "/debug/device"
        with urlopen(url, timeout=10) as resp:
            return json.loads(resp.read())
    with open(source) as f:
        return json.loads(f.read())


def format_report(report: dict) -> str:
    lines: List[str] = []
    add = lines.append
    led = report.get("ledger", {})
    add("device observatory — HBM ledger & shard balance")
    add(f"  total {_mb(led.get('total_bytes'))}"
        f"   live {_mb(led.get('live_bytes'))}"
        f"   peak {_mb(led.get('peak_bytes'))}"
        f"   generations {led.get('generations', 0)}")
    add(f"  forecast at next resize: "
        f"{_mb(led.get('forecast_next_resize_bytes'))}")
    add("")
    by_family = led.get("by_family", {})
    if by_family:
        add("ledger by family (bytes per lifecycle state):")
        for family in sorted(by_family):
            states = by_family[family]
            detail = "  ".join(
                f"{s}={_mb(states[s])}" for s in _STATE_ORDER
                if states.get(s))
            add(f"  {family}: {detail or '-'}")
        add("")
    recon = report.get("reconciliation")
    if recon:
        add("backend reconciliation (jax.device_memory_stats):")
        add(f"  allocator in use {_mb(recon.get('backend_bytes_in_use'))}"
            f"   ledger {_mb(recon.get('ledger_bytes'))}"
            f"   unaccounted {_mb(recon.get('unaccounted_bytes'))}")
        add("")
    elif report.get("backend_devices") == []:
        add("backend reconciliation: unavailable (CPU backend exposes "
            "no allocator stats)")
        add("")
    kernels = report.get("kernels", [])
    if kernels:
        add("kernel registry (dispatches, wall p50/p99):")
        for k in kernels:
            wall = k.get("wall") or {}
            timing = (f"  p50={wall.get('p50', 0):.6f}s"
                      f" p99={wall.get('p99', 0):.6f}s"
                      if wall else "")
            add(f"  {k['kind']:8s} {k['family']:10s}"
                f" x{k['dispatches']}{timing}")
        add("")
    compiles = report.get("compiles", {})
    if compiles:
        add("compiles/retraces: " + ", ".join(
            f"{fam}={n}" for fam, n in sorted(compiles.items())))
        add("")
    bal = report.get("shard_balance")
    if bal:
        skew = bal.get("skew")
        add(f"shard balance ({bal.get('n_shards')} shards, "
            f"skew={skew if skew is None else round(skew, 4)}):")
        add(f"  rows/shard: {bal.get('rows_per_shard')}")
        if bal.get("hot_shards"):
            add(f"  ** hot shards: {bal['hot_shards']} **")
        plan = bal.get("reshard_plan")
        if plan:
            add(f"  recommended reshard: {plan['from_shards']} -> "
                f"{plan['to_shards']} (projected skew "
                f"{plan['projected_skew']:.4f}, {plan['rows_moved']} "
                f"rows over {plan['migration_cells']} cells)")
        add("")
    wm = report.get("watermarks", {})
    if wm:
        add(f"device watermark rung: state={wm.get('state', 'ok')}"
            f"  last={_mb(wm.get('last_bytes'))}"
            f"  soft={_mb(wm.get('soft_bytes')) if wm.get('soft_bytes') else '-'}"
            f"  hard={_mb(wm.get('hard_bytes')) if wm.get('hard_bytes') else '-'}"
            f"  transitions={wm.get('transitions', 0)}")
    return "\n".join(lines)


def breaches(report: dict, skew_threshold: float) -> List[str]:
    """Exit-1 conditions: occupancy at/over the hard device watermark,
    or shard skew at/over the alert threshold."""
    out: List[str] = []
    total = float(report.get("ledger", {}).get("total_bytes", 0))
    hard = float(report.get("watermarks", {}).get("hard_bytes", 0) or 0)
    if hard and total >= hard:
        out.append(f"HBM occupancy {_mb(total)} >= hard watermark "
                   f"{_mb(hard)}")
    bal = report.get("shard_balance") or {}
    skew = bal.get("skew")
    if skew is not None and float(skew) >= skew_threshold:
        out.append(f"shard skew {float(skew):.4f} >= threshold "
                   f"{skew_threshold}")
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("source",
                        help="device URL (http://host:port[/debug/device])"
                             " or a saved JSON file")
    parser.add_argument("--skew-threshold", type=float,
                        default=DEFAULT_SKEW_THRESHOLD,
                        help="shard skew at/over this exits 1 "
                             f"(default {DEFAULT_SKEW_THRESHOLD})")
    args = parser.parse_args(argv)
    try:
        report = load_report(args.source)
    except Exception as e:
        print(f"error: could not read {args.source}: {e}", file=sys.stderr)
        return 2
    print(format_report(report))
    bad = breaches(report, args.skew_threshold)
    for b in bad:
        print(f"** {b} **")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
