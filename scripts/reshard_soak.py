#!/usr/bin/env python
"""Reshard SIGKILL soak: kill a mesh server mid-cutover — after the
range segments are durable but before the merge-back ran — restart it,
replay the WAL, and diff its flush against a never-resharded control.

What it exercises (parallel/reshard.py, "Elastic resharding: live
digest-range migration with WAL-backed exactly-once cutover"):

- the cutover WAL-appends every migrating digest-range cell's captured
  state (metricpb wire, one spool segment per cell) BEFORE any state
  moves onto the new plane;
- a `kill -9` landing between the append and the merge-back loses
  nothing: the restarted process replays the range segments at startup
  — into whatever topology the restart config builds, which this soak
  makes DIFFERENT from the mid-flight target on purpose (the child
  restarts at the old shard count);
- segments are popped only after their merge lands, so the replay is
  exactly-once: a second scan finds an empty spool.

The kill is made deterministic the honest way: the child runs with
`chaos_reshard_cutover_delay_s` high enough that the cutover sleeps
between the appends and the merges, the driver waits until the range
segments are on disk (the appends happened; the merge provably has
not), and THEN delivers SIGKILL. The restarted child runs with chaos
off and replays at start().

The invariant pinned is EXACTNESS: after N kill/restart rounds the
faulted pipeline's flush must match an unfaulted control fed the
identical stream — every family; counters/gauges/llhist/HLL rows
bit-equal; t-digest percentile rows within re-compression tolerance
(the migration re-packs captured centroids once). `ledger_strict` is
on in both children, so any conservation break raises out of flush()
and "FLUSHED" never prints.

Runnable standalone:

    JAX_PLATFORMS=cpu python scripts/reshard_soak.py --kills 2

and from the `reshard`+`slow`-marked soak test (tests/test_reshard.py),
which drives `run_soak()` directly and asserts the report's invariants.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CHILD_ENV_FLAG = "RESHARD_SOAK_CHILD"
SHARDS_OLD = 2
SHARDS_NEW = 3


def wait_until(pred, timeout=120.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


# ---------------------------------------------------------------------------
# child: one mesh server, reshard WAL on, feed protocol over stdin
# ---------------------------------------------------------------------------


def run_child() -> None:
    """Child-process entry: a real mesh Server (strict ledger). Feed
    protocol: metric lines apply on `APPLY`; `RESHARD <n>` starts a
    live reshard (chaos holds the cutover open mid-WAL so the parent
    can SIGKILL provably inside the crash window); `FLUSH` flushes and
    prints the flushed rows as JSON; EOF exits."""
    from veneur_tpu.config import Config
    from veneur_tpu.core.server import Server
    from veneur_tpu.sinks.channel import ChannelMetricSink

    cfg = Config()
    cfg.interval = 3600.0  # flushes are driven by the feed protocol
    cfg.hostname = "reshard-soak"
    cfg.statsd_listen_addresses = []
    cfg.tpu.shards = SHARDS_OLD
    cfg.reshard_spool_dir = os.environ["SOAK_RESHARD_WAL"]
    # acceptance pin: zero unexplained imbalance through the
    # kill/replay cycle — strict raises out of flush(), so "FLUSHED"
    # never prints and the soak fails loudly
    cfg.ledger_strict = True
    cfg.jax_compilation_cache_dir = os.environ.get("SOAK_COMPILE_CACHE", "")
    delay_s = float(os.environ.get("SOAK_CUTOVER_DELAY_S", "0"))
    if delay_s:
        cfg.chaos_enabled = True
        cfg.chaos_reshard_cutover_delay_s = delay_s
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    server = Server(cfg, extra_metric_sinks=[obs])
    server.start()  # replays any range segments a killed round left
    print("READY", flush=True)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        if line == "APPLY":
            server.store.apply_all_pending()
            print("APPLIED", flush=True)
        elif line.startswith("RESHARD "):
            server.reshard.begin(shards=int(line.split()[1]),
                                 deadline_s=600.0)
            print("RESHARD_STARTED", flush=True)
        elif line == "FLUSH":
            server.store.apply_all_pending()
            server.flush()
            rows = {f"{m.name}|{','.join(sorted(m.tags))}": float(m.value)
                    for m in obs.drain()}
            print("FLUSHED " + json.dumps(rows, sort_keys=True),
                  flush=True)
        else:
            server.handle_metric_packet(line.encode())
    server.config.flush_on_shutdown = False
    server.shutdown()
    print("DONE", flush=True)


# ---------------------------------------------------------------------------
# parent: the kill loop
# ---------------------------------------------------------------------------


def _spawn_child(wal_dir: str, cutover_delay_s: float,
                 compile_cache: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        CHILD_ENV_FLAG: "1",
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=8"),
        "SOAK_RESHARD_WAL": wal_dir,
        "SOAK_CUTOVER_DELAY_S": str(cutover_delay_s),
        "SOAK_COMPILE_CACHE": compile_cache,
    })
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        env=env, text=True, bufsize=1)
    assert wait_until(lambda: proc.stdout.readline().strip() == "READY",
                      timeout=300.0), "child never came up"
    return proc


def _feed(proc: subprocess.Popen, lines) -> None:
    for line in lines:
        proc.stdin.write(line + "\n")
    proc.stdin.flush()


def _await(proc: subprocess.Popen, prefix: str, timeout=300.0) -> str:
    box = []

    def got():
        line = proc.stdout.readline().strip()
        if line.startswith(prefix):
            box.append(line)
            return True
        return False
    assert wait_until(got, timeout=timeout), f"no {prefix!r} from child"
    return box[0]


def _flush(proc: subprocess.Popen) -> dict:
    _feed(proc, ["FLUSH"])
    return json.loads(_await(proc, "FLUSHED ")[len("FLUSHED "):])


def _wal_segments(wal_dir: str):
    try:
        return sorted(f for f in os.listdir(wal_dir)
                      if f.endswith(".vspool"))
    except OSError:
        return []


def _compare(faulted: dict, control: dict) -> int:
    """Exact row-for-row equality except t-digest percentile rows
    (re-compressed once by the migration; rtol pins them)."""
    assert set(faulted) == set(control), (
        sorted(set(control) - set(faulted))[:5],
        sorted(set(faulted) - set(control))[:5])
    checked = 0
    for key, want in control.items():
        got = faulted[key]
        if key.split("|", 1)[0].endswith("percentile"):
            assert abs(got - want) <= 1e-6 * max(abs(want), 1e-12), (
                key, got, want)
        else:
            assert got == want, (key, got, want)
        checked += 1
    return checked


def lines_for(round_no: int):
    out = []
    for i in range(16):
        out.append(f"soak.rs.c.{i}:{i + 1 + round_no}|c|#env:soak")
        out.append(f"soak.rs.t.{i}:{10.0 + i + round_no:.1f}|ms")
        out.append(f"soak.rs.ll.{i}:{(round_no * 17 + i) % 91}|l")
        out.append(f"soak.rs.s.{i}:m{(round_no * 7 + i) % 23}|s")
        out.append(f"soak.rs.g.{i}:{i * 1.5 + round_no:.2f}|g")
    return out


def run_soak(kills: int = 2, cutover_delay_s: float = 120.0,
             verbose: bool = False) -> dict:
    """`kills` rounds of feed -> reshard -> SIGKILL-mid-WAL ->
    restart -> replay -> flush-and-diff against an unfaulted control.
    Returns the comparison report; raises AssertionError when an
    invariant breaks."""
    tmp = tempfile.mkdtemp(prefix="reshard-soak-")
    wal_dir = os.path.join(tmp, "reshard-wal")
    cache_dir = os.path.join(tmp, "compile-cache")
    report = {"kills": 0, "restarts": 0, "rounds": []}

    child = None
    ctl = _spawn_child(os.path.join(tmp, "ctl-wal"), 0.0, cache_dir)
    try:
        for round_no in range(kills):
            if child is not None:
                # the previous round's replay child ran chaos-free;
                # each kill round needs the hold-open seam back
                child.kill()
                child.wait()
            child = _spawn_child(wal_dir, cutover_delay_s, cache_dir)
            lines = lines_for(round_no)
            _feed(child, lines + ["APPLY"])
            _await(child, "APPLIED")
            _feed(ctl, lines + ["APPLY"])
            _await(ctl, "APPLIED")
            before = set(_wal_segments(wal_dir))
            _feed(child, [f"RESHARD {SHARDS_NEW}"])
            _await(child, "RESHARD_STARTED")
            # the WAL appends land, then chaos holds the cutover open:
            # the moment fresh segments are on disk the merge provably
            # has not run — kill -9 now, inside the crash window
            assert wait_until(
                lambda: set(_wal_segments(wal_dir)) - before,
                timeout=600.0), "range segments never appeared"
            child.kill()
            child.wait()
            report["kills"] += 1
            # restart with chaos OFF at the OLD shard count: start()
            # replays the log into a topology that differs from the
            # killed cutover's target on purpose
            child = _spawn_child(wal_dir, 0.0, cache_dir)
            report["restarts"] += 1
            assert wait_until(lambda: not _wal_segments(wal_dir),
                              timeout=30.0), "reshard WAL did not drain"
            # post-restart ingest keeps landing, then the diff
            post = lines_for(round_no + 100)
            _feed(child, post + ["APPLY"])
            _await(child, "APPLIED")
            _feed(ctl, post + ["APPLY"])
            _await(ctl, "APPLIED")
            rows = _compare(_flush(child), _flush(ctl))
            if verbose:
                print(f"round {round_no}: killed mid-WAL, replayed, "
                      f"{rows} flush rows match")
            report["rounds"].append({"round": round_no, "rows": rows})
    finally:
        for proc in (child, ctl):
            try:
                proc.kill()
            except (OSError, AttributeError):
                pass
    return report


def main(argv=None) -> int:
    if os.environ.get(CHILD_ENV_FLAG):
        run_child()
        return 0
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--kills", type=int, default=2)
    ap.add_argument("--cutover-delay-s", type=float, default=120.0)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    report = run_soak(kills=args.kills,
                      cutover_delay_s=args.cutover_delay_s,
                      verbose=args.verbose)
    print(json.dumps(report, indent=2))
    print(f"ok: {report['kills']} kill(s), {report['restarts']} "
          f"restart(s), zero loss, flush bit-identical to control")
    return 0


if __name__ == "__main__":
    sys.exit(main())
