#!/bin/bash
# Tunnel watcher: probe the axon TPU tunnel until it answers, then
# immediately capture TPU bench artifacts — the full default run first
# (mixed + sustained@100k + device + config suite, the artifact the
# record needs), then per-scenario extras while the tunnel stays up.
# One scenario per process so a mid-capture wedge only loses that stage.
# Usage: tunnel_capture.sh [outdir]
set -u
OUT=${1:-/tmp/tpu_capture}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/watch.log"; }

# shared probe verdict: bench._write_probe_state is the one writer
# (cwd is the repo root, so `import bench` resolves)
mark() { python -c "import bench; bench._write_probe_state($1, 'axon')"; }
while true; do
  if timeout 30 env JAX_PLATFORMS=axon python -c \
      "import jax; d=jax.devices(); assert d and d[0].platform != 'cpu'" \
      >/dev/null 2>&1; then
    log "tunnel alive"
    mark True
    break
  fi
  log "wedged; retry in 60s"
  mark False
  sleep 60
done

log "capturing default (full artifact)"
JAX_PLATFORMS=axon BENCH_DEADLINE_S=520 timeout 540 python bench.py \
  > "$OUT/default.json" 2> "$OUT/default.err"
log "default rc=$? $(head -c 300 "$OUT/default.json")"

for sc in device forward ssf hll timers counter; do
  grep -q '"platform": "tpu"' "$OUT/default.json" || true
  log "capturing $sc"
  JAX_PLATFORMS=axon BENCH_DEADLINE_S=240 BENCH_DEVICE_SWEEP=1 \
    timeout 260 python bench.py --scenario $sc --duration 4 \
    > "$OUT/$sc.json" 2> "$OUT/$sc.err"
  log "$sc rc=$? $(head -c 200 "$OUT/$sc.json")"
  # a wedge mid-suite: stop burning 240s timeouts on a dead tunnel
  grep -q '"platform": "tpu"' "$OUT/$sc.json" || { log "lost tunnel; stop"; break; }
done
log "capture pass done"
