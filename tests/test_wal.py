"""Durable interval WAL & timestamp-faithful backfill replay tests:
interval-stamped segments, write-ahead-of-send ordering, exactly-once
crash replay via stable per-segment tokens, quarantine bounding and
accounting, the backfill plane's interval buckets and original-
timestamp emission, replay rate-limit isolation, and the in-process
crash drill the acceptance criteria pin (kill mid-flush, restart,
replay — zero counter loss, llhist registers bit-identical to an
unfaulted control, zero unexplained ledger imbalance under
ledger_strict)."""

import os
import shutil
import time

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.forward.backfill import BackfillPlane
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.forward.wire import (INTERVAL_KEY, IDEMPOTENCY_KEY,
                                     stamp_interval_wire)
from veneur_tpu.samplers.metrics import MetricType
from veneur_tpu.testing.forwardtest import ForwardTestServer
from veneur_tpu.util.spool import QUARANTINE_DIR, CarryoverSpool

pytestmark = pytest.mark.wal


def wait_until(fn, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def mkmetric(name, value=1, tags=(), interval=0):
    pbm = metric_pb2.Metric(name=name, type=metric_pb2.Counter,
                            scope=metric_pb2.Global)
    pbm.tags.extend(tags)
    pbm.counter.value = value
    if interval:
        pbm.interval = int(interval)
    return pbm


def mk_server(**kw):
    """The in-process Server pattern (no listeners, manual flush)."""
    from veneur_tpu.core.server import Server
    from veneur_tpu.sinks.channel import ChannelMetricSink

    cfg = Config()
    cfg.interval = 60.0
    cfg.hostname = "test"
    cfg.statsd_listen_addresses = []
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in kw.items():
        setattr(cfg, k, v)
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[obs]), obs


class _LedgerSpy:
    """Minimal ledger double recording note() calls."""

    def __init__(self):
        self.notes = []

    def note(self, stage, n, key=""):
        self.notes.append((stage, n, key))


# -------------------------------------------------------------------------
# WAL segment format: interval stamps, restart survival
# -------------------------------------------------------------------------


class TestWalSegments:
    def test_interval_stamp_survives_restart(self, tmp_path):
        spool = CarryoverSpool(str(tmp_path))
        spool.append([b"m1"], interval_unix=1700000123.5)
        spool.append([b"m2"])  # unstamped legacy append still works
        seg = spool.oldest()
        assert seg.interval_unix == pytest.approx(1700000123.5)

        replayed = CarryoverSpool(str(tmp_path))
        assert replayed.replayed_total == 2
        assert replayed.oldest().interval_unix == \
            pytest.approx(1700000123.5)
        assert replayed.segments()[1].interval_unix == 0.0

    def test_three_restart_ordering_with_corrupt_head(self, tmp_path):
        """Satellite pin: the seq reseed must hold across THREE
        restarts with interleaved appends, and a corrupt-HEAD segment
        must quarantine (accounted) instead of wedging the order."""
        a = CarryoverSpool(str(tmp_path))
        a.append([b"s1a", b"s1b"], interval_unix=100.0)
        a.append([b"s2"], interval_unix=110.0)

        b = CarryoverSpool(str(tmp_path))            # restart 1
        assert b.replayed_total == 2
        b.append([b"s3"], interval_unix=120.0)

        # corrupt the HEAD segment's body on disk (header intact, so
        # the next scan still admits it — the corruption surfaces at
        # read_metrics time, like a torn sector would)
        head = b.oldest()
        with open(head.path, "r+b") as f:
            f.readline()
            f.write(b"\xff\xff\xff\xff")

        c = CarryoverSpool(str(tmp_path))            # restart 2
        assert c.replayed_total == 3
        c.append([b"s4"], interval_unix=130.0)
        names = sorted(os.path.basename(s.path) for s in c.segments())
        seqs = [int(n.split("-")[1]) for n in names]
        assert seqs == sorted(seqs) and len(set(seqs)) == 4
        assert seqs[-1] >= 4  # never reused a predecessor's sequence

        # drain: the corrupt head quarantines, the rest read in order
        drained = []
        for seg in c.segments():
            try:
                drained.append(seg.read_metrics())
            except ValueError:
                c.discard(seg)
        assert drained == [[b"s2"], [b"s3"], [b"s4"]]
        assert c.quarantine_depth == 1
        assert c.quarantined_metrics == 2  # s1a + s1b, still inventoried
        assert c.quarantined_bytes > 0
        qdir = os.path.join(str(tmp_path), QUARANTINE_DIR)
        assert len([f for f in os.listdir(qdir)
                    if f.endswith(".vspool")]) == 1

        # restart 3: quarantine accounting (and the seq floor) survive
        d = CarryoverSpool(str(tmp_path))
        assert d.quarantine_depth == 1
        assert d.quarantined_metrics == 2
        d.append([b"s5"])
        assert int(os.path.basename(
            d.segments()[-1].path).split("-")[1]) > seqs[-1]

    def test_unreadable_at_scan_quarantines(self, tmp_path):
        bad = tmp_path / "spill-00000001-junk.vspool"
        bad.write_bytes(b"not a header\n\xff")
        spool = CarryoverSpool(str(tmp_path))
        assert spool.depth == 0
        assert spool.quarantine_depth == 1
        # count unknown: never entered the books, stock contribution 0
        assert spool.quarantined_metrics == 0

    def test_quarantine_bound_purges_oldest(self, tmp_path):
        ledger = _LedgerSpy()
        spool = CarryoverSpool(str(tmp_path), quarantine_max_segments=2,
                               ledger=ledger)
        for i in range(3):
            spool.append([b"x%d" % i, b"y%d" % i])
        for seg in spool.segments():
            spool.discard(seg)
        assert spool.quarantine_depth == 2
        assert spool.quarantine_purged_total == 1
        assert spool.quarantine_purged_metrics_total == 2
        # the purge is explained shed; the quarantine moves are NOT
        sheds = [n for n in ledger.notes if n[0] == "forward.shed"]
        assert sheds == [("forward.shed", 2, "quarantine_purged")]

    def test_quarantine_byte_bound(self, tmp_path):
        spool = CarryoverSpool(str(tmp_path), quarantine_max_bytes=150,
                               quarantine_max_segments=100)
        for i in range(3):
            spool.append([b"z" * 100])
        for seg in spool.segments():
            spool.discard(seg)
        assert spool.quarantined_bytes <= 150
        assert spool.quarantine_purged_total >= 1

    def test_telemetry_rows_include_quarantine(self, tmp_path):
        spool = CarryoverSpool(str(tmp_path))
        spool.append([b"q"])
        spool.discard(spool.oldest())
        rows = {name: value for name, _k, value, _t
                in spool.telemetry_rows()}
        assert rows["carryover.spool.quarantined"] == 1.0
        assert rows["carryover.spool.quarantined_bytes"] > 0
        assert rows["carryover.spool.quarantine_purged"] == 0.0


# -------------------------------------------------------------------------
# WAL-mode forward client
# -------------------------------------------------------------------------


def mk_client(address, spool, **kw):
    from veneur_tpu.forward.client import ForwardClient
    from veneur_tpu.util.resilience import CircuitBreaker, RetryPolicy

    kw.setdefault("retry", RetryPolicy(max_attempts=1))
    kw.setdefault("breaker",
                  CircuitBreaker(failure_threshold=10_000, name="t"))
    return ForwardClient(address, deadline=3.0, spool=spool, wal=True,
                         **kw)


def one_counter(name="wal.cnt", value=1.0):
    from veneur_tpu.core.columnstore import RowMeta
    from veneur_tpu.core.flusher import ForwardableState
    from veneur_tpu.samplers.metrics import MetricScope

    meta = RowMeta(name=name, tags=[], joined_tags="", digest32=1,
                   scope=MetricScope.GLOBAL_ONLY, wire_type="counter")
    return ForwardableState(counters=[(meta, value)])


class TestForwardWal:
    def test_append_rides_ahead_of_send(self, tmp_path):
        """WAL mode: the interval reaches disk before any RPC, every
        send carries the interval stamp + a spool-derived token, and a
        delivered segment leaves the log."""
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        spool = CarryoverSpool(str(tmp_path))
        client = mk_client(ft.address, spool)
        try:
            t0 = 1700000000.0
            got = client.forward(one_counter(value=7.0), interval_start=t0)
            assert got == 1
            assert client.wal_appended_metrics == 1
            assert client.wal_acked_metrics == 1
            assert spool.depth == 0
            assert [p.counter.value for p in received] == [7]
            # the segment bytes were field-11 stamped too
            assert received[0].interval == int(t0)
            md = ft.call_metadata[-1]
            assert md[INTERVAL_KEY] == f"{t0:.3f}"
            assert md[IDEMPOTENCY_KEY].startswith("spool:")
        finally:
            client.close()
            ft.stop()

    def test_crash_before_send_replays_on_restart(self, tmp_path):
        """Process dies after the append, before the send: a fresh
        client over the same directory delivers the interval."""
        spool = CarryoverSpool(str(tmp_path))
        client = mk_client("127.0.0.1:1", spool)  # dead upstream
        t0 = time.time() - 5.0
        assert client.forward(one_counter(value=3.0),
                              interval_start=t0) == 0
        assert spool.depth == 1  # durable, undelivered
        client.close()  # "kill -9"

        received = []
        ft = ForwardTestServer(received.extend, address="127.0.0.1:0")
        ft.start()
        spool2 = CarryoverSpool(str(tmp_path))
        assert spool2.replayed_total == 1
        client2 = mk_client(ft.address, spool2)
        try:
            from veneur_tpu.core.flusher import ForwardableState
            assert client2.forward(ForwardableState()) == 1
            assert spool2.depth == 0
            assert [p.counter.value for p in received] == [3]
            assert received[0].interval == int(t0)
            md = ft.call_metadata[-1]
            assert md[INTERVAL_KEY] == f"{t0:.3f}"
        finally:
            client2.close()
            ft.stop()

    def test_replay_is_exactly_once_via_stable_token(self, tmp_path):
        """A segment whose send landed but whose ack was lost (crash
        between send and pop) re-sends with the SAME token after
        restart, and the receiver's dedupe drops it: at-least-once
        replay, exactly-once merge."""
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.server import ImportServer

        glob, gobs = mk_server()
        imp = ImportServer(glob, "127.0.0.1:0")
        imp.start()
        try:
            # ack-lost simulation: append, copy the segment aside (its
            # name IS the token), drain, restore the copy = the crash
            # wiped the ack but not the log — then restart and re-drain
            spool = CarryoverSpool(str(tmp_path))
            client = mk_client(imp.address, spool)
            client.forward(one_counter("wal.once", 9.0),
                           interval_start=time.time())
            # appended-but-undrained? no: live WAL drains in the same
            # call, so re-append one undelivered interval by hand
            assert spool.depth == 0
            client.forward(one_counter("wal.once", 9.0),
                           interval_start=time.time())
            client.close()

            spool2 = CarryoverSpool(str(tmp_path / "d2"))
            client2 = mk_client(imp.address, spool2)
            client2.forward(one_counter("wal.twice", 4.0),
                            interval_start=time.time())
            client2.close()
            assert spool2.depth == 0

            # now the real scenario end-to-end in one directory
            spool3 = CarryoverSpool(str(tmp_path / "d3"))
            client3 = mk_client("127.0.0.1:1", spool3)  # dead upstream
            client3.forward(one_counter("wal.exact", 6.0),
                            interval_start=time.time())
            client3.close()
            assert spool3.depth == 1
            seg = spool3.oldest()
            saved = seg.path + ".saved"
            shutil.copyfile(seg.path, saved)

            spool4 = CarryoverSpool(str(tmp_path / "d3"))
            client4 = mk_client(imp.address, spool4)
            assert client4.forward(ForwardableState()) == 1  # delivered
            client4.close()
            os.replace(saved, seg.path)  # the ack never reached disk

            spool5 = CarryoverSpool(str(tmp_path / "d3"))
            assert spool5.replayed_total == 1
            client5 = mk_client(imp.address, spool5)
            before = imp.duplicates_dropped_total
            client5.forward(ForwardableState())
            assert imp.duplicates_dropped_total == before + 1
            assert spool5.depth == 0  # acked (as duplicate) and removed
            client5.close()

            glob.store.apply_all_pending()
            glob.flush()
            got = {m.name: m.value for m in gobs.wait_flush()}
            assert got["wal.exact"] == 6.0  # merged exactly once
            assert got["wal.once"] == 18.0  # two separate intervals
        finally:
            imp.stop()

    def test_stale_replay_throttled_fresh_first(self, tmp_path):
        """Backfill isolation: a stale backlog drains BEHIND the live
        interval and under the replay token bucket, while fresh
        forwards sustain full rate."""
        from veneur_tpu.core.overload import TokenBucket

        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        spool = CarryoverSpool(str(tmp_path))
        now = time.time()
        # a 6-interval-stale backlog (1 metric per segment)
        for i in range(6):
            stamp = now - 3600 + i * 10
            spool.append(
                [stamp_interval_wire(
                    mkmetric(f"stale.{i}", 1).SerializeToString(), stamp)],
                interval_unix=stamp)
        limiter = TokenBucket(1.0, 1.0)  # ~1 stale metric/second
        client = mk_client(ft.address, spool, replay_limiter=limiter,
                           replay_stale_after=60.0)
        try:
            got = client.forward(one_counter("live.cnt", 2.0),
                                 interval_start=now)
            # the live interval landed despite the backlog, plus the
            # first stale segment (progress guarantee) and whatever the
            # bucket's initial burst admitted
            names = [p.name for p in received]
            assert "live.cnt" in names
            assert got >= 2
            assert spool.depth >= 3  # most of the backlog deferred
            assert client.wal_replay_throttled >= 1
            # fresh-first: the live segment beat every stale one out
            assert names[0] == "live.cnt"

            # the backlog trickles out across later intervals
            from veneur_tpu.core.flusher import ForwardableState
            deadline = time.time() + 30.0
            while spool.depth and time.time() < deadline:
                client.forward(ForwardableState())
                time.sleep(0.5)
            assert spool.depth == 0
            assert sorted(p.name for p in received if p.name != "live.cnt") \
                == sorted(f"stale.{i}" for i in range(6))
        finally:
            client.close()
            ft.stop()


# -------------------------------------------------------------------------
# Backfill plane: interval buckets, original-timestamp emission
# -------------------------------------------------------------------------


class TestBackfillPlane:
    def test_counters_sum_gauges_lww_per_interval(self):
        plane = BackfillPlane(percentiles=(0.5,))
        ledger = _LedgerSpy()
        plane.ledger = ledger
        t1, t2 = 1700000000, 1700000060
        assert plane.merge_proto(mkmetric("bf.c", 3), t1)
        assert plane.merge_proto(mkmetric("bf.c", 4), t1)
        assert plane.merge_proto(mkmetric("bf.c", 9), t2)
        g = metric_pb2.Metric(name="bf.g", type=metric_pb2.Gauge)
        g.gauge.value = 1.5
        assert plane.merge_proto(g, t1)
        g2 = metric_pb2.Metric(name="bf.g", type=metric_pb2.Gauge)
        g2.gauge.value = 2.5
        assert plane.merge_proto(g2, t1)
        assert plane.open_intervals == 2
        assert plane.open_metrics == 5

        plane.drain()              # generation roll: nothing closes yet
        out = plane.drain()        # now both buckets are idle -> close
        assert plane.open_intervals == 0
        by = {(m.name, m.timestamp): m for m in out}
        assert by[("bf.c", t1)].value == 7.0
        assert by[("bf.c", t1)].type == MetricType.COUNTER
        assert by[("bf.c", t1)].backfilled is True
        assert by[("bf.c", t2)].value == 9.0
        assert by[("bf.g", t1)].value == 2.5
        # conservation notes: merged == closed
        merged = sum(n for s, n, _k in ledger.notes
                     if s == "backfill.merged")
        closed = sum(n for s, n, _k in ledger.notes
                     if s == "backfill.closed")
        assert merged == closed == 5

    def test_per_metric_field11_beats_rpc_stamp(self):
        plane = BackfillPlane()
        t_rpc, t_field = 1700000000, 1700000300
        assert plane.merge_proto(
            mkmetric("bf.f11", 2, interval=t_field), t_rpc)
        plane.drain()
        out = plane.drain()
        assert out[0].timestamp == t_field

    def test_llhist_register_add_is_exact(self):
        from veneur_tpu.forward import llhistwire
        from veneur_tpu.ops import llhist_ref

        plane = BackfillPlane(percentiles=(0.5,))
        t = 1700000000
        bins_a = np.zeros(llhist_ref.BINS, np.int64)
        bins_b = np.zeros(llhist_ref.BINS, np.int64)
        bins_a[llhist_ref.bin_index(np.array([12.0]))[0]] = 5
        bins_b[llhist_ref.bin_index(np.array([12.0]))[0]] = 2
        bins_b[llhist_ref.bin_index(np.array([120.0]))[0]] = 1
        for bins in (bins_a, bins_b):
            pbm = metric_pb2.Metric(name="bf.ll", type=metric_pb2.LLHist)
            pbm.llhist.bins = llhistwire.marshal(bins)
            assert plane.merge_proto(pbm, t)
        plane.drain()
        out = plane.drain()
        by_name = {}
        for m in out:
            by_name.setdefault(m.name, []).append(m)
        assert by_name["bf.ll.count"][0].value == 8.0
        assert by_name["bf.ll.count"][0].timestamp == t
        # cumulative buckets: le:+Inf equals the exact register count
        inf = [m for m in by_name["bf.ll.bucket"]
               if "le:+Inf" in m.tags]
        assert inf[0].value == 8.0

    def test_bound_closes_oldest_first(self):
        plane = BackfillPlane(max_open=2)
        stamps = [1700000000 + 60 * i for i in range(3)]
        for i, t in enumerate(stamps):
            plane.merge_proto(mkmetric(f"bf.b{i}", 1), t)
        assert plane.open_intervals == 2
        assert plane.bound_closed_total == 1
        out = plane.drain()  # pending (bound-forced) emission delivers
        assert [m.timestamp for m in out] == [stamps[0]]

    def test_older_than_every_bucket_still_emits(self):
        """Regression: a stamp older than ALL open buckets at the bound
        creates the bucket that is itself the eviction victim — the
        metric must still emit (a one-metric interval) and the books
        must balance, never orphan."""
        ledger = _LedgerSpy()
        plane = BackfillPlane(max_open=2, ledger=ledger)
        plane.merge_proto(mkmetric("bf.new1", 1), 1700001000)
        plane.merge_proto(mkmetric("bf.new2", 1), 1700002000)
        plane.merge_proto(mkmetric("bf.ancient", 1), 1700000500)
        assert plane.open_intervals == 2
        out = plane.drain() + plane.drain() + plane.drain(force=True)
        assert sorted(m.name for m in out) == \
            ["bf.ancient", "bf.new1", "bf.new2"]
        merged = sum(n for s, n, _k in ledger.notes
                     if s == "backfill.merged")
        closed = sum(n for s, n, _k in ledger.notes
                     if s == "backfill.closed")
        assert merged == closed == 3
        assert plane.open_metrics == 0

    def test_unstamped_and_junk_rejected(self):
        plane = BackfillPlane()
        assert not plane.merge_proto(mkmetric("bf.u", 1), 0)
        novalue = metric_pb2.Metric(name="bf.nv")
        assert not plane.merge_proto(novalue, 1700000000)
        assert plane.rejected_total == 2


# -------------------------------------------------------------------------
# End-to-end backfill drill: stale spool -> import -> original timestamps
# -------------------------------------------------------------------------


class TestBackfillEndToEnd:
    def test_stale_spool_replays_with_original_timestamps(self, tmp_path):
        """The acceptance backfill drill (in-process shape): a
        6-interval-stale spool directory replays through the real gRPC
        import plane; the global buckets by ORIGINAL interval and its
        flush emits series timestamped at those intervals, visible in
        Cortex remote-write sample timestamps and Prometheus exposition
        lines; the books close clean under ledger_strict."""
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.server import ImportServer
        from veneur_tpu.sinks.prometheus import render_exposition

        glob, gobs = mk_server(ledger_strict=True)
        assert glob.backfill is not None
        imp = ImportServer(glob, "127.0.0.1:0")
        imp.start()

        # a dead peer's spool directory: 6 intervals, hours stale
        now = time.time()
        stamps = [int(now - 7200 + 60 * i) for i in range(6)]
        spool = CarryoverSpool(str(tmp_path))
        for i, t in enumerate(stamps):
            metrics = [stamp_interval_wire(
                mkmetric("restore.cnt", 10 + i).SerializeToString(), t)]
            spool.append(metrics, interval_unix=t)
        del spool

        restored = CarryoverSpool(str(tmp_path))
        assert restored.replayed_total == 6
        client = mk_client(imp.address, restored)
        try:
            assert client.forward(ForwardableState()) == 6
            assert restored.depth == 0
            assert glob.backfill.open_intervals == 6
            assert glob.backfill.open_metrics == 6

            glob.flush()  # generation roll
            glob.flush()  # idle buckets close -> backfilled emission
            flushed = gobs.drain()
            backfilled = [m for m in flushed if m.backfilled]
            got = {m.timestamp: m.value for m in backfilled
                   if m.name == "restore.cnt"}
            assert got == {t: float(10 + i)
                           for i, t in enumerate(stamps)}

            # Cortex remote-write: per-sample timestamps are the
            # ORIGINAL interval starts (milliseconds)
            from veneur_tpu.sinks.cortex import CortexMetricSink
            cortex = CortexMetricSink("cortex", "http://unused/", "host")
            series = [cortex._series(m) for m in backfilled
                      if m.name == "restore.cnt"]
            assert sorted(ts for _l, _v, ts in series) == \
                [t * 1000 for t in stamps]

            # Prometheus exposition: backfilled lines carry explicit
            # millisecond timestamps; live lines stay bare
            text = render_exposition(backfilled)
            for t in stamps:
                assert f" {t * 1000}" in text
            live = render_exposition(
                [m for m in flushed if not m.backfilled][:5])
            for t in stamps:
                assert f" {t * 1000}" not in live
            # OpenMetrics negotiation stamps SECONDS, not milliseconds
            om = render_exposition(backfilled, openmetrics=True)
            for t in stamps:
                assert f" {t}" in om
                assert f" {t * 1000}" not in om
        finally:
            client.close()
            imp.stop()


# -------------------------------------------------------------------------
# Crash drill: kill mid-flush, restart, replay — exactness pinned
# -------------------------------------------------------------------------


class TestCrashDrill:
    def test_crash_restart_replay_is_exact(self, tmp_path):
        """In-process acceptance drill: three rounds of append-then-die
        (the send never completes), each followed by a restart+replay;
        final global state must equal an unfaulted control's — counter
        sums exact, llhist registers bit-identical — and every ledger
        interval closes with zero unexplained imbalance (strict)."""
        from veneur_tpu.forward.server import ImportServer

        faulted, _fobs = mk_server(ledger_strict=True)
        control, _cobs = mk_server(ledger_strict=True)
        f_imp = ImportServer(faulted, "127.0.0.1:0")
        f_imp.start()
        c_imp = ImportServer(control, "127.0.0.1:0")
        c_imp.start()

        def mk_local(forward_to):
            local, _ = mk_server(forward_address="127.0.0.1:1")
            return local

        f_local = mk_local(f_imp.address)
        c_local = mk_local(c_imp.address)
        c_client = mk_client(c_imp.address,
                             CarryoverSpool(str(tmp_path / "control")))
        c_local.forwarder = c_client.forward
        wal_dir = str(tmp_path / "wal")

        def feed(server, round_no):
            for i in range(30):
                server.handle_metric_packet(
                    b"drill.cnt.%d:3|c|#veneurglobalonly" % (i % 5))
                server.handle_metric_packet(
                    b"drill.llh.%d:%d|l" % (i % 3, (round_no * 13 + i) % 87))
            server.store.apply_all_pending()

        try:
            for round_no in range(3):
                feed(f_local, round_no)
                feed(c_local, round_no)
                c_local.flush()

                # faulted path: forward to a dead port — the WAL append
                # lands, the send cannot; then the "process" dies
                dead_spool = CarryoverSpool(wal_dir)
                dead_client = mk_client("127.0.0.1:1", dead_spool)
                f_local.forwarder = dead_client.forward
                f_local.forward_client = dead_client
                f_local.flush()
                assert dead_spool.depth >= 1
                dead_client.close()  # kill -9

                # restart: fresh objects over the same WAL directory
                re_spool = CarryoverSpool(wal_dir)
                assert re_spool.replayed_total >= 1
                re_client = mk_client(f_imp.address, re_spool)
                f_local.forwarder = re_client.forward
                # forward_client drives the empty-snapshot dispatch:
                # pending WAL segments alone must trigger the drain
                f_local.forward_client = re_client
                f_local.flush()  # empty snapshot still drains the WAL
                assert re_spool.depth == 0
                re_client.close()

            # the diff: counters exact, llhist registers bit-identical
            for server in (faulted, control):
                server.store.apply_all_pending()

            def counter_sums(server):
                vals, touched, meta = \
                    server.store.counters.snapshot_and_reset()
                return {meta[r].name: float(np.asarray(vals)[r])
                        for r in np.flatnonzero(np.asarray(touched)).tolist()
                        if meta[r] is not None}

            def llhist_bins(server):
                _out, bins, touched, meta = \
                    server.store.llhists.snapshot_and_reset((0.5,))
                rows = np.flatnonzero(np.asarray(touched)).tolist()
                return {meta[row].name: np.asarray(bins)[i]
                        for i, row in enumerate(rows)
                        if meta[row] is not None}

            f_sums, c_sums = counter_sums(faulted), counter_sums(control)
            assert f_sums == c_sums and f_sums  # zero counter loss
            f_bins, c_bins = llhist_bins(faulted), llhist_bins(control)
            assert set(f_bins) == set(c_bins) and f_bins
            for name in f_bins:
                assert np.array_equal(f_bins[name], c_bins[name]), name

            # strict close on both receivers: zero unexplained imbalance
            faulted.ledger.close_interval()
            control.ledger.close_interval()
        finally:
            c_client.close()
            f_imp.stop()
            c_imp.stop()


# -------------------------------------------------------------------------
# Satellites: compilation cache, retrace cache tags
# -------------------------------------------------------------------------


class TestCompilationCache:
    def test_knob_points_jax_at_directory(self, tmp_path):
        import jax

        cache_dir = str(tmp_path / "jit-cache")
        server, _ = mk_server(jax_compilation_cache_dir=cache_dir)
        assert server.enable_compilation_cache() is True
        assert jax.config.jax_compilation_cache_dir == cache_dir
        assert os.path.isdir(cache_dir)
        assert server.telemetry.events.snapshot(
            kind="compilation_cache_enabled")

    def test_disabled_without_directory(self):
        server, _ = mk_server()
        assert server.enable_compilation_cache() is False

    def test_retrace_tags_carry_cache_outcome(self, tmp_path):
        cache_dir = tmp_path / "jit-cache"
        cache_dir.mkdir()
        server, _ = mk_server(jax_compilation_cache_dir=str(cache_dir))
        # miss: the recompile ADDED a cache entry
        server._store_resize("counter", 64, 128, 0.01, kind="resize")
        (cache_dir / "jit_x-abc-cache").write_bytes(b"x")
        server._store_resize("counter", 64, 128, 0.5, kind="recompile")
        # hit: no new entries appeared during the recompile
        server._store_resize("gauge", 64, 128, 0.01, kind="resize")
        server._store_resize("gauge", 64, 128, 0.02, kind="recompile")
        drained = server.latency.drain_retraces()
        assert drained["counter"][1] == "miss"
        assert drained["gauge"][1] == "hit"


# -------------------------------------------------------------------------
# SIGKILL soak: the real kill -9 mid-flush loop (slow)
# -------------------------------------------------------------------------


@pytest.mark.slow
class TestCrashReplaySoak:
    def test_sigkill_soak_zero_loss(self):
        """Drive scripts/crash_replay_soak.py: SIGKILL a real local
        child mid-flush (fresh WAL segment on disk, send hanging in
        the chaos seam) twice, restart, replay — final global state
        diffs clean against the unfaulted control."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "crash_replay_soak",
            os.path.join(os.path.dirname(__file__), os.pardir,
                         "scripts", "crash_replay_soak.py"))
        soak = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(soak)
        report = soak.run_soak(kills=2, counters_per_round=20)
        assert report["kills"] == 2 and report["restarts"] == 2
        assert report["counters"]  # nonempty and already diffed exact


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v"] + sys.argv[1:]))
