"""Tests for the auxiliary ingest planes: gRPC (DogStatsD packets + SSF
spans), TLS TCP with mutual auth, and unique-timeseries accounting."""

from __future__ import annotations

import socket
import ssl
import subprocess
import time

import grpc
import pytest

from veneur_tpu import ssf
from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink, ChannelSpanSink


def make_server(**cfg_kwargs):
    cfg = Config()
    cfg.interval = 100.0
    for k, v in cfg_kwargs.items():
        setattr(cfg, k, v)
    cfg.apply_defaults()
    ch = ChannelMetricSink()
    spans = ChannelSpanSink()
    server = Server(cfg, extra_metric_sinks=[ch], extra_span_sinks=[spans])
    server.start()
    return server, ch, spans


def flushed(server, ch):
    server.flush()
    return {m.name: m for m in ch.wait_flush()}


class TestGrpcIngest:
    def test_send_packet_and_span(self):
        server, ch, spans = make_server(
            grpc_listen_addresses=["127.0.0.1:0"])
        try:
            addr = server.grpc_ingest_servers[0].address
            chan = grpc.insecure_channel(addr)
            send_packet = chan.unary_unary(
                "/dogstatsd.DogstatsdGRPC/SendPacket",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            from veneur_tpu.core.protos import dogstatsd_pb2
            pkt = dogstatsd_pb2.DogstatsdPacket(
                packetBytes=b"grpc.count:7|c\ngrpc.gauge:1.5|g")
            send_packet(pkt.SerializeToString())

            span = ssf.SSFSpan(
                id=5, trace_id=5, name="op", service="svc",
                start_timestamp=1, end_timestamp=2)
            send_span = chan.unary_unary(
                "/ssf.SSFGRPC/SendSpan",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            send_span(span.SerializeToString())
            chan.close()

            deadline = time.time() + 5
            while time.time() < deadline and not spans.spans:
                time.sleep(0.02)
            # assert before flush(): the channel span sink drains its
            # buffer into the queue on every flush
            assert any(s.name == "op" for s in spans.spans)
            metrics = flushed(server, ch)
            assert metrics["grpc.count"].value == 7
            assert metrics["grpc.gauge"].value == 1.5
        finally:
            server.shutdown()


def _openssl(*args):
    subprocess.run(["openssl", *args], check=True, capture_output=True)


@pytest.fixture(scope="module")
def tls_certs(tmp_path_factory):
    """Self-signed CA + server and client certs (the reference ships
    equivalent fixtures in testdata/*.pem for TestTCPConfig)."""
    d = tmp_path_factory.mktemp("tls")
    ca_key, ca_crt = d / "ca.key", d / "ca.crt"
    _openssl("req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(ca_key), "-out", str(ca_crt),
             "-days", "1", "-subj", "/CN=test-ca")
    for who, cn in (("server", "127.0.0.1"), ("client", "test-client")):
        key, csr, crt = d / f"{who}.key", d / f"{who}.csr", d / f"{who}.crt"
        _openssl("req", "-newkey", "rsa:2048", "-nodes",
                 "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={cn}")
        ext = d / f"{who}.ext"
        ext.write_text("subjectAltName=IP:127.0.0.1\n" if who == "server"
                       else "extendedKeyUsage=clientAuth\n")
        _openssl("x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
                 "-CAkey", str(ca_key), "-CAcreateserial",
                 "-out", str(crt), "-days", "1", "-extfile", str(ext))
    return d


class TestTLSTCP:
    def _server(self, certs, require_client_cert: bool):
        from veneur_tpu.util.secret import StringSecret
        kwargs = dict(
            statsd_listen_addresses=["tcp://127.0.0.1:0"],
            tls_certificate=(certs / "server.crt").read_text(),
            tls_key=StringSecret((certs / "server.key").read_text()),
        )
        if require_client_cert:
            kwargs["tls_authority_certificate"] = (
                certs / "ca.crt").read_text()
        return make_server(**kwargs)

    def _connect(self, certs, addr, with_client_cert: bool):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(cafile=str(certs / "ca.crt"))
        if with_client_cert:
            ctx.load_cert_chain(str(certs / "client.crt"),
                                str(certs / "client.key"))
        raw = socket.create_connection(addr, timeout=5)
        return ctx.wrap_socket(raw, server_hostname="127.0.0.1")

    def test_tls_roundtrip(self, tls_certs):
        server, ch, _ = self._server(tls_certs, require_client_cert=False)
        try:
            conn = self._connect(tls_certs, server.local_addr("tcp"), False)
            conn.sendall(b"tls.count:3|c\n")
            conn.close()
            deadline = time.time() + 5
            while (time.time() < deadline
                   and server.stats["packets_received"] < 1):
                time.sleep(0.02)
            assert flushed(server, ch)["tls.count"].value == 3
        finally:
            server.shutdown()

    def test_mutual_auth_requires_client_cert(self, tls_certs):
        server, ch, _ = self._server(tls_certs, require_client_cert=True)
        try:
            addr = server.local_addr("tcp")
            # with client cert: accepted
            conn = self._connect(tls_certs, addr, True)
            conn.sendall(b"mtls.count:1|c\n")
            conn.close()
            # without a client cert the server rejects the handshake; with
            # TLS 1.3 the client may only see the alert (or a reset) on
            # first read — either way, the packet must not be ingested
            try:
                conn2 = self._connect(tls_certs, addr, False)
                conn2.sendall(b"mtls.count:100|c\n")
                conn2.recv(1)
                conn2.close()
            except (ssl.SSLError, ConnectionError, OSError):
                pass
            deadline = time.time() + 5
            while (time.time() < deadline
                   and server.stats["packets_received"] < 1):
                time.sleep(0.02)
            assert flushed(server, ch)["mtls.count"].value == 1
        finally:
            server.shutdown()


class TestUniqueTimeseries:
    def test_exact_count(self):
        server, ch, _ = make_server(count_unique_timeseries=True)
        try:
            server.handle_packet_batch([
                b"a:1|c\na:2|c\nb:1|g\nc:1:2|ms\nd:x|s\nd:y|s",
                b"a:1|c|#tag:one",  # distinct timeseries (tags differ)
            ])
            assert server.store.unique_timeseries() == 5
            server.flush()
            ch.wait_flush()
            # interval-scoped: resets after flush
            assert server.store.unique_timeseries() == 0
        finally:
            server.shutdown()
