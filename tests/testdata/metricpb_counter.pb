
fixture.gcount*cH