"""CLI tests: veneur-emit packet rendering + end-to-end against a real
server, veneur config validation, veneur-prometheus conversion
(reference cmd/veneur-emit/main_test.go patterns)."""

import socket
import time

import pytest

from veneur_tpu.cmd import veneur_emit as emit
from veneur_tpu.cmd.veneur import main as veneur_main
from test_server import generate_config, setup_server


class TestPacketRendering:
    def test_metric(self):
        assert emit.render_metric_packet("a.b", 3, "c", []) == b"a.b:3|c"
        assert emit.render_metric_packet(
            "a.b", 2.5, "g", ["x:y", "z"], rate=0.5) == \
            b"a.b:2.5|g|@0.5|#x:y,z"

    def test_event(self):
        pkt = emit.render_event_packet(
            "tt", "hello world", ["env:prod"], priority="low",
            alert_type="error")
        assert pkt.startswith(b"_e{2,11}:tt|hello world")
        assert b"p:low" in pkt
        assert b"t:error" in pkt
        assert pkt.endswith(b"#env:prod")

    def test_service_check(self):
        pkt = emit.render_service_check_packet(
            "db.up", 2, ["shard:1"], message="down")
        assert pkt == b"_sc|db.up|2|#shard:1|m:down"

    def test_parse_hostport(self):
        assert emit.parse_hostport("udp://1.2.3.4:99") == ("udp", "1.2.3.4", 99)
        assert emit.parse_hostport("tcp://h:1") == ("tcp", "h", 1)
        assert emit.parse_hostport("127.0.0.1:8126") == \
            ("udp", "127.0.0.1", 8126)


class TestEmitEndToEnd:
    def _server_with_udp(self):
        cfg = generate_config()
        cfg.statsd_listen_addresses = ["udp://127.0.0.1:0"]
        cfg.ssf_listen_addresses = ["udp://127.0.0.1:0"]
        server, observer = setup_server(cfg)
        server.start()
        return server, observer

    def _wait_metric(self, server, observer, name, timeout=5.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            time.sleep(0.05)
            server.flush()
            try:
                flushed = observer.wait_flush(timeout=0.2)
            except Exception:
                continue
            for metric in flushed:
                if metric.name == name:
                    return metric
        raise AssertionError(f"{name} never arrived")

    def test_emit_counter_udp(self):
        server, observer = self._server_with_udp()
        try:
            host, port = server.local_addr("udp")
            rc = emit.main(["-hostport", f"udp://{host}:{port}",
                            "-name", "emit.test", "-count", "4",
                            "-tag", "a:b"])
            assert rc == 0
            metric = self._wait_metric(server, observer, "emit.test")
            assert metric.value == 4.0
            assert "a:b" in metric.tags
        finally:
            server.shutdown()

    def test_emit_command_timing(self):
        server, observer = self._server_with_udp()
        try:
            host, port = server.local_addr("udp")
            rc = emit.main(["-hostport", f"udp://{host}:{port}",
                            "-name", "cmd.timer",
                            "-command", "true"])
            assert rc == 0
            metric = self._wait_metric(server, observer, "cmd.timer.max")
            assert metric.value >= 0
        finally:
            server.shutdown()

    def test_emit_command_propagates_exit_code(self):
        server, _ = self._server_with_udp()
        try:
            host, port = server.local_addr("udp")
            rc = emit.main(["-hostport", f"udp://{host}:{port}",
                            "-name", "cmd.timer",
                            "-command", "false"])
            assert rc != 0
        finally:
            server.shutdown()

    def test_emit_ssf_metric(self):
        # reference -ssf: the metric ships as an SSF sample on a
        # metrics-only span and lands in aggregation via extraction
        server, observer = self._server_with_udp()
        try:
            host, port = server.local_addr("ssf-udp")
            rc = emit.main(["-hostport", f"udp://{host}:{port}",
                            "-name", "emit.ssf.c", "-count", "7",
                            "-tag", "k:v", "-ssf"])
            assert rc == 0
            metric = self._wait_metric(server, observer, "emit.ssf.c")
            assert metric.value == 7.0
            assert "k:v" in metric.tags
        finally:
            server.shutdown()

    def test_emit_event_sc_reference_flags(self):
        # the reference flag set (-e_time/-e_aggr_key/-e_event_tags,
        # -sc_time/-sc_hostname/-sc_tags) renders packets the parser
        # accepts
        from veneur_tpu.samplers.parser import Parser

        sent = []
        real = emit.send_packet
        emit.send_packet = lambda hp, pkt: sent.append(pkt)
        try:
            rc = emit.main(["-mode", "event", "-e_title", "T",
                            "-e_text", "B", "-e_time", "1700000000",
                            "-e_aggr_key", "agg", "-e_event_tags",
                            "x:1,y:2"])
            assert rc == 0
            rc = emit.main(["-mode", "sc", "-sc_name", "svc.ok",
                            "-sc_status", "1", "-sc_time", "1700000000",
                            "-sc_hostname", "h1", "-sc_tags", "z:3",
                            "-sc_msg", "degraded"])
            assert rc == 0
        finally:
            emit.send_packet = real
        from veneur_tpu.samplers.parser import (
            EVENT_AGGREGATION_KEY_TAG_KEY, STATUS_WARNING)

        parser = Parser()
        ev = parser.parse_event(sent[0])
        assert ev.name == "T" and ev.message == "B"
        assert ev.timestamp == 1700000000
        assert ev.tags[EVENT_AGGREGATION_KEY_TAG_KEY] == "agg"
        assert ev.tags["x"] == "1" and ev.tags["y"] == "2"
        sc = parser.parse_service_check(sent[1])
        assert sc.key.name == "svc.ok" and sc.value == STATUS_WARNING
        assert sc.hostname == "h1" and "z:3" in sc.tags
        assert sc.timestamp == 1700000000

    def test_emit_span_reference_flags(self):
        # -trace_id/-parent_span_id/-span_starttime/-span_endtime/
        # -indicator/-error/-span_tags (reference tracing flag set)
        from veneur_tpu.ssf.protos import ssf_pb2

        sent = []
        sock_cls = emit.socket.socket

        class FakeSock:
            def __init__(self, *a, **k):
                pass

            def sendto(self, data, addr):
                sent.append(data)

            def close(self):
                pass

        emit.socket.socket = FakeSock
        try:
            rc = emit.main(["-mode", "span", "-name", "em.sp",
                            "-trace_id", "42", "-parent_span_id", "41",
                            "-span_starttime", "1700000000",
                            "-span_endtime", "1700000001",
                            "-indicator", "-error",
                            "-span_tags", "st:1"])
            assert rc == 0
        finally:
            emit.socket.socket = sock_cls
        span = ssf_pb2.SSFSpan.FromString(sent[0])
        assert span.trace_id == 42 and span.parent_id == 41
        assert span.indicator and span.error
        assert span.end_timestamp - span.start_timestamp == int(1e9)
        assert span.tags["st"] == "1"

    def test_emit_span_ssf(self):
        server, observer = self._server_with_udp()
        try:
            host, port = server.local_addr("ssf-udp")
            rc = emit.main(["-hostport", f"udp://{host}:{port}",
                            "-mode", "span", "-name", "em.span",
                            "-span_service", "emit-svc",
                            "-span_duration", "0.05"])
            assert rc == 0
            deadline = time.time() + 5
            while time.time() < deadline and not server.stats.get(
                    "packets_received"):
                time.sleep(0.05)
            # the span reached the span channel / workers
            time.sleep(0.2)
            assert server.spans_dropped == 0
        finally:
            server.shutdown()


class TestVeneurCLI:
    def test_version(self, capsys):
        assert veneur_main(["-version"]) == 0
        import veneur_tpu
        assert veneur_tpu.__version__ in capsys.readouterr().out

    def test_validate_config(self, tmp_path, capsys):
        p = tmp_path / "cfg.yaml"
        p.write_text("interval: 5s\nhostname: x\n")
        assert veneur_main(["-f", str(p), "-validate-config"]) == 0
        assert "config OK" in capsys.readouterr().out

    def test_validate_config_strict_rejects_unknown(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("interval: 5s\nnot_a_real_field: 1\n")
        assert veneur_main(["-f", str(p),
                            "-validate-config-strict"]) == 1

    def test_go_runtime_profiler_keys_accepted_strict(self, tmp_path):
        # reference config.go:14,35 — a migrated config carrying the Go
        # runtime profiler rates must stay valid under strict validation
        p = tmp_path / "cfg.yaml"
        p.write_text("interval: 5s\nblock_profile_rate: 1000\n"
                     "mutex_profile_fraction: 5\n")
        assert veneur_main(["-f", str(p),
                            "-validate-config-strict"]) == 0
        from veneur_tpu.config import read_config
        cfg = read_config(str(p), strict=True)
        assert cfg.block_profile_rate == 1000
        assert cfg.mutex_profile_fraction == 5


class TestVeneurPrometheus:
    def test_statsd_emitter(self):
        from veneur_tpu.cmd.veneur_prometheus import StatsdEmitter
        from veneur_tpu.samplers.metrics import MetricKey, UDPMetric
        from veneur_tpu.samplers import metrics as m
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5.0)
        port = recv.getsockname()[1]
        emitter = StatsdEmitter(f"127.0.0.1:{port}", prefix="pfx.")
        emitter.ingest_metric(UDPMetric(
            key=MetricKey(name="up", type=m.GAUGE), value=1.0,
            tags=["a:b"]))
        data, _ = recv.recvfrom(65536)
        assert data == b"pfx.up:1.0|g|#a:b"
        recv.close()


class TestExampleConfigs:
    def test_shipped_examples_validate(self):
        """The annotated example configs must stay loadable — they are
        the documented starting points (reference example*.yaml)."""
        import os
        from veneur_tpu.cmd.veneur import main as veneur_main
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for name in ("example.yaml", "example_host.yaml"):
            path = os.path.join(root, "examples", name)
            assert veneur_main(["-f", path, "-validate-config"]) == 0, name

    def test_proxy_example_parses(self):
        import os

        import yaml
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        raw = yaml.safe_load(
            open(os.path.join(root, "examples", "example_proxy.yaml")))
        assert raw["grpc_address"]
        assert "forward_address" in raw


class TestEmitSpanDuration:
    def test_start_without_end_uses_duration(self):
        import veneur_tpu.cmd.veneur_emit as emit
        from veneur_tpu.ssf.protos import ssf_pb2

        sent = []
        sock_cls = emit.socket.socket

        class FakeSock:
            def __init__(self, *a, **k):
                pass

            def sendto(self, data, addr):
                sent.append(data)

            def close(self):
                pass

        emit.socket.socket = FakeSock
        try:
            assert emit.main(["-mode", "span", "-name", "d.sp",
                              "-span_starttime", "1700000000",
                              "-span_duration", "5"]) == 0
        finally:
            emit.socket.socket = sock_cls
        span = ssf_pb2.SSFSpan.FromString(sent[0])
        assert span.start_timestamp == 1700000000 * 10**9
        assert span.end_timestamp - span.start_timestamp == 5 * 10**9
