"""SSF subsystem tests: wire framing, sample conversion, span pipeline,
metric extraction, trace client (reference protocol/wire_test.go,
parser ParseMetricSSF tests, ssfmetrics tests, server_test.go:1240-1352)."""

import io
import socket
import time

import pytest

from veneur_tpu import protocol, ssf, trace
from veneur_tpu.samplers.metrics import MetricScope
from veneur_tpu.samplers.parser import Parser

from test_server import generate_config, setup_server


def mkspan(**kw):
    defaults = dict(id=5, trace_id=6, parent_id=2,
                    start_timestamp=1_000_000_000,
                    end_timestamp=5_000_000_000,
                    name="spanner", service="svc")
    defaults.update(kw)
    return ssf.SSFSpan(**defaults)


class TestWire:
    def test_roundtrip(self):
        span = mkspan()
        span.metrics.append(ssf.count("x", 1))
        buf = io.BytesIO()
        n = protocol.write_ssf(buf, span)
        assert n == len(buf.getvalue())
        buf.seek(0)
        got = protocol.read_ssf(buf)
        assert got.name == "spanner"
        assert got.metrics[0].name == "x"

    def test_multiple_frames(self):
        buf = io.BytesIO()
        for i in range(3):
            protocol.write_ssf(buf, mkspan(id=i + 1))
        buf.seek(0)
        ids = []
        while True:
            span = protocol.read_ssf(buf)
            if span is None:
                break
            ids.append(span.id)
        assert ids == [1, 2, 3]

    def test_clean_eof(self):
        assert protocol.read_ssf(io.BytesIO(b"")) is None

    def test_bad_version(self):
        with pytest.raises(protocol.FramingError):
            protocol.read_ssf(io.BytesIO(b"\x01\x00\x00\x00\x00"))

    def test_oversize_frame(self):
        hdr = b"\x00" + (protocol.MAX_SSF_PACKET_LENGTH + 1).to_bytes(4, "big")
        with pytest.raises(protocol.FramingError):
            protocol.read_ssf(io.BytesIO(hdr))

    def test_truncated_body_is_framing_error(self):
        buf = io.BytesIO(b"\x00\x00\x00\x00\x0aabc")
        with pytest.raises(protocol.FramingError):
            protocol.read_ssf(buf)

    def test_decode_error_is_not_framing_error(self):
        # a well-framed but undecodable body must not kill the stream
        bad = b"\xff" * 10
        buf = io.BytesIO()
        buf.write(b"\x00" + len(bad).to_bytes(4, "big") + bad)
        protocol.write_ssf(buf, mkspan(id=3))
        buf.seek(0)
        with pytest.raises(protocol.SSFDecodeError):
            protocol.read_ssf(buf)
        # stream is still synchronized: next frame reads fine
        assert protocol.read_ssf(buf).id == 3

    def test_parse_normalization(self):
        span = mkspan(name="")
        span.tags["name"] = "from-tag"
        span.metrics.append(ssf.SSFSample(name="m", value=1))
        got = protocol.parse_ssf(span.SerializeToString())
        assert got.name == "from-tag"
        assert "name" not in got.tags
        assert got.metrics[0].sample_rate == 1.0

    def test_valid_trace(self):
        assert protocol.valid_trace(mkspan())
        assert not protocol.valid_trace(mkspan(id=0))
        assert not protocol.valid_trace(mkspan(name=""))
        assert not protocol.valid_trace(mkspan(end_timestamp=0))


class TestParseMetricSSF:
    def setup_method(self):
        self.parser = Parser()

    def test_counter(self):
        m = self.parser.parse_metric_ssf(ssf.count("c", 2, {"k": "v"}))
        assert (m.name, m.type, m.value) == ("c", "counter", 2.0)
        assert m.tags == ["k:v"]

    def test_set_uses_message(self):
        m = self.parser.parse_metric_ssf(ssf.set_sample("s", "member-1"))
        assert (m.type, m.value) == ("set", "member-1")

    def test_status_uses_status(self):
        m = self.parser.parse_metric_ssf(
            ssf.status("st", ssf.CRITICAL, message="down"))
        assert (m.type, m.value) == ("status", 2)

    def test_scope_enum_and_magic_tags(self):
        s = ssf.gauge("g", 1)
        s.scope = 2
        assert self.parser.parse_metric_ssf(s).scope == MetricScope.GLOBAL_ONLY
        s2 = ssf.gauge("g", 1, {"veneurlocalonly": "true", "a": "b"})
        m = self.parser.parse_metric_ssf(s2)
        assert m.scope == MetricScope.LOCAL_ONLY
        assert m.tags == ["a:b"]

    def test_timing_value_is_in_resolution_units(self):
        t = ssf.timing("t", 1.5, 1e-3)  # 1.5s at ms resolution
        m = self.parser.parse_metric_ssf(t)
        assert m.value == pytest.approx(1500.0)
        assert m.type == "histogram"

    def test_indicator_metrics(self):
        span = mkspan(indicator=True, error=True)
        out = self.parser.convert_indicator_metrics(span, "ind", "obj")
        byname = {m.name: m for m in out}
        assert byname["ind"].value == pytest.approx(4e9)  # 4s in ns
        assert "error:true" in byname["ind"].tags
        assert byname["obj"].scope == MetricScope.GLOBAL_ONLY
        assert "objective:spanner" in byname["obj"].tags

    def test_indicator_metrics_skips_non_indicator(self):
        assert self.parser.convert_indicator_metrics(mkspan(), "i", "o") == []

    def test_objective_override_tag(self):
        span = mkspan(indicator=True)
        span.tags["ssf_objective"] = "custom"
        out = self.parser.convert_indicator_metrics(span, "", "obj")
        assert "objective:custom" in out[0].tags


class TestSpanPipeline:
    def test_extraction_to_flush(self):
        """Samples inside a span reach the aggregation path and flush."""
        server, observer = setup_server()
        span = mkspan()
        span.metrics.append(ssf.count("span.counter", 7))
        span.metrics.append(ssf.gauge("span.gauge", 1.25))
        server.metric_extraction.ingest(span)
        server.flush()
        got = {m.name: m for m in observer.wait_flush()}
        assert got["span.counter"].value == 7.0
        assert got["span.gauge"].value == 1.25

    def test_indicator_span_produces_timers(self):
        cfg = generate_config()
        cfg.indicator_span_timer_name = "indicator.timer"
        server, observer = setup_server(cfg)
        server.metric_extraction.ingest(mkspan(indicator=True))
        server.flush()
        names = {m.name for m in observer.wait_flush()}
        assert any(n.startswith("indicator.timer") for n in names)

    def test_span_worker_fanout(self):
        server, observer = setup_server()
        got = []

        class CollectSink:
            def name(self):
                return "collect"

            def kind(self):
                return "collect"

            def start(self, srv):
                pass

            def ingest(self, span):
                got.append(span.id)

            def flush(self):
                pass

            def stop(self):
                pass

        server.span_sinks.append(CollectSink())
        server.start()
        try:
            server.ingest_span(mkspan(id=77))
            deadline = time.time() + 2
            while not got and time.time() < deadline:
                time.sleep(0.01)
            assert got == [77]
        finally:
            server.shutdown()

    def test_sink_worker_chunk_semantics(self):
        """_SpanSinkWorker accounting: span-counted capacity, whole-chunk
        drops, batch delivery through ingest_many, drain-on-stop."""
        from veneur_tpu.core.server import _SpanSinkWorker

        got = []

        class BatchSink:
            def name(self):
                return "batch"

            def ingest(self, span):
                raise AssertionError("batch path should be used")

            def ingest_many(self, spans):
                got.extend(spans)

        w = _SpanSinkWorker(BatchSink(), capacity=100)
        w.submit_many(list(range(60)))
        w.submit_many(list(range(50)))   # 60+50 > 100: dropped whole
        assert w.dropped == 50
        w.submit_many(list(range(40)))   # fits exactly
        w.start()
        deadline = time.time() + 2
        while len(got) < 100 and time.time() < deadline:
            time.sleep(0.01)
        assert len(got) == 100 and w.ingested == 100
        # spans submitted before stop() are drained, not abandoned
        w.submit_many([1, 2, 3])
        w.stop()
        assert len(got) == 103

    def test_ssf_udp_ingest(self):
        cfg = generate_config()
        cfg.ssf_listen_addresses = ["udp://127.0.0.1:0"]
        server, observer = setup_server(cfg)
        server.start()
        try:
            addr = server.local_addr("ssf-udp")
            span = mkspan()
            span.metrics.append(ssf.count("udp.span.counter", 3))
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.sendto(span.SerializeToString(), addr)
            deadline = time.time() + 2
            while (server.metric_extraction.spans_processed == 0
                   and time.time() < deadline):
                time.sleep(0.01)
            assert server.metric_extraction.spans_processed == 1
            server.flush()
            got = {m.name for m in observer.wait_flush()}
            assert "udp.span.counter" in got
        finally:
            server.shutdown()

    def test_ssf_framed_tcp_ingest(self):
        cfg = generate_config()
        cfg.ssf_listen_addresses = ["tcp://127.0.0.1:0"]
        server, observer = setup_server(cfg)
        server.start()
        try:
            addr = server.local_addr("ssf-tcp")
            sock = socket.create_connection(addr)
            f = sock.makefile("wb")
            protocol.write_ssf(f, mkspan(id=11))
            protocol.write_ssf(f, mkspan(id=12))
            f.flush()
            deadline = time.time() + 2
            while (server.metric_extraction.spans_processed < 2
                   and time.time() < deadline):
                time.sleep(0.01)
            assert server.metric_extraction.spans_processed == 2
            sock.close()
        finally:
            server.shutdown()


class TestTraceClient:
    def test_channel_backend_loopback(self):
        server, observer = setup_server()
        server.start()
        try:
            client = trace.Client(trace.ChannelBackend(server.ingest_span))
            with client.start_span("op", service="svc") as span:
                span.add(ssf.count("traced.counter", 2))
            client.flush()
            deadline = time.time() + 2
            while (server.metric_extraction.spans_processed == 0
                   and time.time() < deadline):
                time.sleep(0.01)
            server.flush()
            got = {m.name for m in observer.wait_flush()}
            assert "traced.counter" in got
            client.close()
        finally:
            server.shutdown()

    def test_span_lineage(self):
        client = trace.neutralized_client()
        parent = client.start_span("parent", service="s")
        child = parent.child("child")
        assert child.trace_id == parent.trace_id
        assert child.proto.parent_id == parent.id
        assert child.id != parent.id
        client.close()

    def test_error_flag_on_exception(self):
        client = trace.neutralized_client()
        recorded = []
        client.record = recorded.append
        with pytest.raises(RuntimeError):
            with client.start_span("boom", service="s"):
                raise RuntimeError("x")
        assert recorded[0].error is True
        client.close()

    def test_udp_backend(self):
        rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rx.bind(("127.0.0.1", 0))
        rx.settimeout(2)
        client = trace.Client(trace.UDPBackend(rx.getsockname()))
        with client.start_span("udp-span", service="s"):
            pass
        client.flush()
        data, _ = rx.recvfrom(65536)
        got = protocol.parse_ssf(data)
        assert got.name == "udp-span"
        client.close()
        rx.close()


class TestTraceMaxLength:
    def test_config_cap_closes_oversized_frame_stream(self):
        """trace_max_length_bytes bounds accepted SSF frames (reference
        server.go:498): an oversized frame is a framing error and the
        stream closes without the span being ingested."""
        cfg = generate_config()
        cfg.ssf_listen_addresses = ["tcp://127.0.0.1:0"]
        cfg.trace_max_length_bytes = 32
        server, _ = setup_server(cfg)
        server.start()
        try:
            addr = server.local_addr("ssf-tcp")
            sock = socket.create_connection(addr)
            f = sock.makefile("wb")
            big = mkspan(id=21)
            big.tags["pad"] = "x" * 128  # encodes well past 32 bytes
            protocol.write_ssf(f, big)
            f.flush()
            # server must hang up on the framing violation
            sock.settimeout(5)
            assert sock.recv(1) == b""
            assert server.metric_extraction.spans_processed == 0
            sock.close()

            # frames under the cap still flow on a new connection
            sock2 = socket.create_connection(addr)
            f2 = sock2.makefile("wb")
            protocol.write_ssf(f2, mkspan(id=22))
            f2.flush()
            deadline = time.time() + 5
            while (server.metric_extraction.spans_processed < 1
                   and time.time() < deadline):
                time.sleep(0.01)
            assert server.metric_extraction.spans_processed == 1
            sock2.close()
        finally:
            server.shutdown()
