"""Ingest admission control, overload degradation & pipeline supervision
(core/overload.py): unit coverage of the token bucket / watermark ladder
/ kernel-drop parsing / supervisor, server-level shed-ladder semantics,
the /healthcheck/ready degradation surface, and the acceptance soak —
20 rounds at 30 % injected ingest faults under a hard memory watermark
with exact loss accounting."""

import logging
import socket
import threading
import time
import urllib.request

import pytest

from veneur_tpu.config import Config
from veneur_tpu.core import overload as ov
from veneur_tpu.core.overload import (
    DEGRADED, OK, SHEDDING, KernelDropMonitor, OverloadManager, Supervisor,
    TokenBucket, WatermarkMonitor)
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink
from veneur_tpu.util.chaos import Chaos

pytestmark = pytest.mark.chaos


def make_config(**overrides) -> Config:
    cfg = Config()
    cfg.interval = 10.0
    cfg.hostname = "test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


def wait_until(fn, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def by_name(metrics):
    out = {}
    for metric in metrics:
        out.setdefault(metric.name, []).append(metric)
    return out


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class TestTokenBucket:
    def test_zero_rate_admits_everything(self):
        b = TokenBucket(0, 0)
        assert all(b.admit() for _ in range(10_000))

    def test_burst_then_refusal(self):
        clock = FakeClock()
        b = TokenBucket(rate=10, burst=5, clock=clock)
        assert sum(b.admit() for _ in range(10)) == 5

    def test_refill_over_time(self):
        clock = FakeClock()
        b = TokenBucket(rate=10, burst=5, clock=clock)
        for _ in range(5):
            b.admit()
        assert not b.admit()
        clock.t += 0.5  # refills 5 tokens
        assert sum(b.admit() for _ in range(10)) == 5

    def test_batch_admission_all_or_nothing(self):
        clock = FakeClock()
        b = TokenBucket(rate=10, burst=10, clock=clock)
        assert b.admit(8)
        assert not b.admit(8)  # only 2 left
        assert b.admit(2)


class TestKernelDropMonitor:
    PROC = (
        "  sl  local_address rem_address   st tx_queue rx_queue tr "
        "tm->when retrnsmt   uid  timeout inode ref pointer drops\n"
        "   0: 0100007F:1F90 00000000:0000 07 00000000:00000000 00:00000000"
        " 00000000  1000        0 12345 2 ffff000000000000 7\n"
        "   1: 00000000:0035 00000000:0000 07 00000000:00000000 00:00000000"
        " 00000000  1000        0 99999 2 ffff000000000000 0\n")

    def test_parse_proc_udp(self):
        drops = KernelDropMonitor.parse_proc_udp(self.PROC)
        assert drops == {12345: 7, 99999: 0}

    def test_poll_accumulates_deltas_not_absolutes(self, monkeypatch):
        mon = KernelDropMonitor()
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.bind(("127.0.0.1", 0))
            import os
            inode = os.fstat(s.fileno()).st_ino
            mon.watch_socket(s, "udp:test")
            readings = iter([{inode: 10}, {inode: 10}, {inode: 17},
                             {inode: 17}])
            monkeypatch.setattr(mon, "_read_proc",
                                lambda: next(readings))
            # first sighting: pre-existing drops are baseline, not ours
            assert mon.poll() == 0
            assert mon.poll() == 0
            assert mon.poll() == 7
            assert mon.poll() == 0
            assert mon.totals() == {"udp:test": 7}

    def test_real_proc_poll_is_harmless(self):
        # on Linux this reads the real /proc/net/udp; elsewhere it is a
        # no-op — either way nothing raises and totals stay consistent
        mon = KernelDropMonitor()
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.bind(("127.0.0.1", 0))
            mon.watch_socket(s, "udp:real")
            mon.poll()
            mon.poll()
            assert mon.totals().get("udp:real", 0) >= 0


class TestWatermarkMonitor:
    def test_ladder_transitions_and_recovery(self):
        edges = []
        mon = WatermarkMonitor(soft_bytes=100, hard_bytes=200,
                               on_transition=lambda o, n, r:
                               edges.append((o, n)))
        assert mon.observe(50) == OK
        assert mon.observe(150) == DEGRADED
        assert mon.observe(250) == SHEDDING
        # recovery is immediate: one reading below soft returns to ok
        assert mon.observe(50) == OK
        assert edges == [(OK, DEGRADED), (DEGRADED, SHEDDING),
                         (SHEDDING, OK)]
        assert mon.transitions == 3

    def test_disabled_watermarks_never_leave_ok(self):
        mon = WatermarkMonitor(soft_bytes=0, hard_bytes=0)
        assert mon.observe(10**15) == OK

    def test_tick_includes_chaos_pressure(self):
        chaos = Chaos(ingest_rss_bytes=0)
        mon = WatermarkMonitor(soft_bytes=1, hard_bytes=10**14,
                               pressure=chaos.simulated_rss_bytes)
        assert mon.tick() == DEGRADED  # real RSS alone clears 1 byte
        chaos.set_simulated_rss(10**14)
        assert mon.tick() == SHEDDING
        chaos.set_simulated_rss(0)
        assert mon.tick() == DEGRADED


class TestSupervisor:
    def test_stall_detected_and_recovers(self, caplog):
        clock = FakeClock()
        stalls = []
        sup = Supervisor(deadline=1.0, escalation_deadline=0.0,
                         on_stall=lambda n, a: stalls.append(n),
                         escalate=lambda n, a: pytest.fail("escalated"),
                         clock=clock)
        sup.register("pump")
        assert sup.check() == []
        clock.t += 2.0
        with caplog.at_level(logging.ERROR, "veneur_tpu.overload"):
            assert sup.check() == ["pump"]
        assert any("pump stalled" in r.message for r in caplog.records)
        assert stalls == ["pump"]
        assert sup.stall_counts == {"pump": 1}
        # flagged once, not once per poll
        assert sup.check() == []
        # a heartbeat clears the stall; the next stall re-flags
        sup.beat("pump")
        assert sup.stalled_components() == []
        clock.t += 2.0
        assert sup.check() == ["pump"]
        assert sup.stall_counts == {"pump": 2}

    def test_per_component_deadline_override(self):
        clock = FakeClock()
        sup = Supervisor(deadline=0.5, clock=clock)
        sup.register("fast")
        sup.register("slow", deadline=10.0)
        clock.t += 1.0
        assert sup.check() == ["fast"]  # slow's override not exceeded

    def test_default_escalation_reports_through_crash_machinery(
            self, monkeypatch):
        """_hard_abort notifies the registered crash reporters (the
        Sentry seam) before the hard exit."""
        from veneur_tpu.core.overload import _hard_abort
        from veneur_tpu.util import crash

        reported = []
        exits = []
        crash.register_reporter(lambda exc, tb: reported.append(str(exc)))
        monkeypatch.setattr(ov.os, "_exit", exits.append)
        try:
            _hard_abort("pump", 12.0)
        finally:
            crash.clear_reporters()
        assert exits == [3]
        assert reported and "pump stalled for 12.0s" in reported[0]

    def test_escalation_after_deadline(self):
        clock = FakeClock()
        escalated = []
        sup = Supervisor(deadline=1.0, escalation_deadline=5.0,
                         escalate=lambda n, a: escalated.append(n),
                         clock=clock)
        sup.register("pump")
        clock.t += 2.0
        sup.check()  # flagged, but not yet escalated
        assert escalated == []
        clock.t += 5.0
        sup.check()
        assert escalated == ["pump"]

    def test_probe_advances_surface_as_stalls(self):
        clock = FakeClock()
        sup = Supervisor(deadline=100.0, clock=clock)
        value = [0]
        sup.add_probe("pump-native", lambda: value[0])
        sup.check()
        assert sup.probe_stalls == {"pump-native": 0}
        value[0] = 3
        sup.check()
        assert sup.probe_stalls == {"pump-native": 3}
        value[0] = 5
        sup.check()
        assert sup.probe_stalls == {"pump-native": 5}

    def test_unregister_drops_probes_too(self):
        """A probe closure keeps its owner (the native Pump) alive: a
        closed listener's unregister must remove it, or the pump leaks
        and a restart double-registers under the same name."""
        clock = FakeClock()
        sup = Supervisor(deadline=100.0, clock=clock)
        sup.register("pump")
        sup.add_probe("pump", lambda: 5)
        sup.check()
        sup.unregister("pump")
        assert sup._probes == []
        assert sup.probe_stalls == {}
        sup.check()  # no stale probe polled
        assert sup.probe_stalls == {}

    def test_disabled_supervisor_never_starts(self):
        sup = Supervisor(deadline=0.0)
        sup.start()
        assert sup._thread is None
        sup.stop()


class TestShedLadder:
    """Server-level: the priority ladder drops spans first, then
    histogram/set samples, and never counter/gauge deltas."""

    def _pressured_server(self, state_bytes, **overrides):
        cfg = make_config(
            chaos_enabled=True,
            overload_watermark_soft_bytes=10**13,
            overload_watermark_hard_bytes=2 * 10**13,
            overload_watermark_poll=0.05, **overrides)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        if state_bytes:
            server.chaos.set_simulated_rss(state_bytes)
        server.overload.watermarks.tick()  # apply without the thread
        return server

    def test_shedding_keeps_counters_and_gauges_sheds_histo_set(self):
        server = self._pressured_server(3 * 10**13)
        try:
            assert server.overload.state == SHEDDING
            server.handle_metric_packet(b"lad.c:5|c")
            server.handle_metric_packet(b"lad.g:7|g")
            server.handle_metric_packet(b"lad.h:1|ms")
            server.handle_metric_packet(b"lad.s:x|s")
            server.flush()
            got = by_name(server.metric_sinks[0].wait_flush())
            assert got["lad.c"][0].value == 5.0
            assert got["lad.g"][0].value == 7.0
            assert not any(n.startswith(("lad.h", "lad.s")) for n in got)
            shed = server.overload.shed_total
            assert shed.get("histogram|overload") == 1
            assert shed.get("set|overload") == 1
        finally:
            server.shutdown()

    def test_any_degradation_pauses_span_ingest(self):
        server = self._pressured_server(int(1.5 * 10**13))
        try:
            assert server.overload.state == DEGRADED
            before = server.span_chan.qsize()
            server.ingest_span(object())
            assert server.span_chan.qsize() == before  # shed, not queued
            assert server.overload.shed_total.get("span|overload") == 1
        finally:
            server.shutdown()

    def test_degraded_tightens_histogram_sampling(self):
        server = self._pressured_server(
            int(1.5 * 10**13), overload_watermark_degraded_keep=0.25)
        try:
            assert server.overload.state == DEGRADED
            for _ in range(100):
                server.handle_metric_packet(b"deg.h:1|ms")
            shed = server.overload.shed_total.get("histogram|degraded", 0)
            assert shed == 75  # keep-1-in-4 is deterministic
        finally:
            server.shutdown()

    def test_ok_state_sheds_nothing(self):
        server = self._pressured_server(0)
        try:
            assert server.overload.state == OK
            server.handle_metric_packet(b"ok.h:1|ms")
            server.handle_metric_packet(b"ok.s:x|s")
            server.ingest_span(object())
            assert server.overload.shed_total == {}
        finally:
            server.shutdown()

    def test_over_limit_statsd_batch_keeps_counters(self):
        """Rate-limited BATCHES parse in essential-only mode: histogram
        and set columns shed with exact per-class sample counts,
        counter/gauge deltas kept. Admission is batch-granular (one
        token take per parsed batch, cost = its sample count)."""
        cfg = make_config(ingest_rate_limit_statsd=1.0,
                          ingest_rate_limit_burst=1.0)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        try:
            # the bucket holds exactly 1 token (the clamped batch ask):
            # the first batch is clean, the rest are over-limit
            for _ in range(5):
                server.handle_packet_batch([b"rl.c:1|c\nrl.h:1|ms"])
            server.flush()
            got = by_name(server.metric_sinks[0].wait_flush())
            assert got["rl.c"][0].value == 5.0         # every delta kept
            hist = [n for n in got if n.startswith("rl.h")]
            shed = server.overload.shed_total.get("histogram|rate_limit", 0)
            assert shed == 4                            # over-limit sheds
            assert any("count" in n for n in hist)      # clean one kept
        finally:
            server.shutdown()

    def test_span_rate_limit_sheds_and_counts(self):
        cfg = make_config(ingest_rate_limit_spans=1.0,
                          ingest_rate_limit_burst=1.0)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        try:
            for _ in range(4):
                server.ingest_span(object())
            assert server.span_chan.qsize() == 1
            assert server.overload.shed_total.get("span|rate_limit") == 3
        finally:
            server.shutdown()


class TestChaosIngestFaults:
    def test_mangle_is_seeded_deterministic(self):
        def run(seed):
            c = Chaos(seed=seed, ingest_drop_rate=0.2,
                      ingest_truncate_rate=0.2, ingest_duplicate_rate=0.2)
            return c.mangle_packets([b"pkt.a:1|c"] * 200)

        assert run(3) == run(3)
        assert run(3) != run(4)

    def test_fault_accounting_is_exact(self):
        c = Chaos(seed=11, ingest_drop_rate=0.3, ingest_truncate_rate=0.2,
                  ingest_duplicate_rate=0.1)
        sent = [b"acc.c:1|c"] * 1000
        out = c.mangle_packets(sent)
        pf = c.packet_faults
        assert len(out) == (1000 - pf.get("drop", 0)
                            - 0  # truncated packets survive, shorter
                            + pf.get("duplicate", 0))
        truncated = [p for p in out if p != b"acc.c:1|c"]
        assert len(truncated) == pf.get("truncate", 0)
        assert all(len(p) < len(b"acc.c:1|c") for p in truncated)

    def test_one_byte_packets_never_count_phantom_truncates(self):
        c = Chaos(seed=5, ingest_truncate_rate=1.0)
        out = c.mangle_packets([b"x"] * 50)
        assert out == [b"x"] * 50  # can't shorten: passed untouched
        assert c.packet_faults.get("truncate", 0) == 0

    def test_truncate_always_shortens(self):
        c = Chaos(seed=6, ingest_truncate_rate=1.0)
        out = c.mangle_packets([b"some.metric:1|c"] * 200)
        assert len(out) == 200
        assert all(1 <= len(p) < len(b"some.metric:1|c") for p in out)

    def test_no_faults_planned_is_identity(self):
        c = Chaos(seed=1)
        batch = [b"x:1|c"]
        assert c.mangle_packets(batch) is batch

    def test_telemetry_rows_include_packet_faults(self):
        c = Chaos(seed=2, ingest_drop_rate=1.0)
        c.mangle_packets([b"x:1|c"])
        rows = c.telemetry_rows()
        assert ("chaos.packet_faults", "counter", 1.0,
                ["action:drop"]) in rows


class TestReadyDegradation:
    def _http_get(self, addr, path):
        host, port = addr
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=5) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def test_ready_answers_503_with_reason_while_shedding(self):
        cfg = make_config(http_address="127.0.0.1:0", chaos_enabled=True,
                          overload_watermark_soft_bytes=10**13,
                          overload_watermark_hard_bytes=2 * 10**13,
                          overload_watermark_poll=0.05)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            addr = server.http_api.address
            status, body = self._http_get(addr, "/healthcheck/ready")
            assert status == 200
            server.chaos.set_simulated_rss(3 * 10**13)
            assert wait_until(
                lambda: server.overload.state == SHEDDING, timeout=5.0)
            status, body = self._http_get(addr, "/healthcheck/ready")
            assert status == 503
            import json
            payload = json.loads(body)
            assert payload["ready"] is False
            assert "shedding" in payload["reason"]
            # /metrics carries the ladder state for scrapers
            _, metrics = self._http_get(addr, "/metrics")
            assert b"veneur_overload_state 2" in metrics
            # release: back to ok within one poll interval, ready again
            server.chaos.set_simulated_rss(0)
            assert wait_until(
                lambda: server.overload.state == OK, timeout=5.0)
            status, _ = self._http_get(addr, "/healthcheck/ready")
            assert status == 200
        finally:
            server.shutdown()

    def test_ready_fails_while_flush_watchdog_tripped(self):
        # interval 60s: neither the flush loop (which would reset
        # last_flush_unix) nor the watchdog thread (which would abort
        # the whole process, os._exit) ticks during the test window
        cfg = make_config(http_address="127.0.0.1:0", interval=60.0,
                          flush_watchdog_missed_flushes=2)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            addr = server.http_api.address
            status, _ = self._http_get(addr, "/healthcheck/ready")
            assert status == 200
            # simulate a wedged flush loop: the last flush recedes past
            # the 2-interval watchdog budget
            server.last_flush_unix = time.time() - 2.1 * 60.0
            status, body = self._http_get(addr, "/healthcheck/ready")
            assert status == 503
            assert b"watchdog" in body
        finally:
            server.shutdown()

    def test_overload_transitions_hit_the_flight_recorder(self):
        cfg = make_config(chaos_enabled=True,
                          overload_watermark_soft_bytes=10**13,
                          overload_watermark_hard_bytes=2 * 10**13)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        try:
            server.chaos.set_simulated_rss(3 * 10**13)
            server.overload.watermarks.tick()
            events = server.telemetry.events.snapshot(
                kind="overload_state")
            assert events and events[-1]["new"] == SHEDDING
        finally:
            server.shutdown()


class TestSupervisorInServer:
    def test_stalled_pipeline_thread_detected_and_exported(self, caplog):
        """Acceptance pin: a deliberately stalled ingest-pipeline thread
        is detected within supervisor_deadline, logged at ERROR, and
        exported as a stall metric."""
        # deadline must clear the span worker's idle beat period (the
        # 0.5 s queue-poll timeout), or a healthy-but-idle worker could
        # be flagged before the wedge even lands
        cfg = make_config(supervisor_deadline=1.0, supervisor_poll=0.05)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        release = threading.Event()

        def wedge(span):
            release.wait(20.0)

        server.metric_extraction.ingest = wedge  # stalls the span worker
        server.start()
        try:
            with caplog.at_level(logging.ERROR, "veneur_tpu.overload"):
                server.ingest_span(object())
                deadline = (cfg.supervisor_deadline
                            + 4 * cfg.supervisor_poll + 1.0)
                assert wait_until(
                    lambda: server.overload.supervisor.stall_counts.get(
                        "span-worker-0", 0) >= 1, timeout=deadline), \
                    "supervisor never flagged the wedged span worker"
                # the counter increments just before the log call: wait
                # for the record too rather than racing it
                assert wait_until(lambda: any(
                    "span-worker-0 stalled" in r.getMessage()
                    for r in caplog.records), timeout=2.0)
            exposition = server.telemetry.registry.render_prometheus()
            assert ('veneur_supervisor_stalls_total'
                    '{component="span-worker-0"}') in exposition
            events = server.telemetry.events.snapshot(
                kind="pipeline_stall")
            assert events and events[-1]["component"] == "span-worker-0"
        finally:
            release.set()
            server.shutdown()

    def test_healthy_server_reports_no_stalls(self):
        cfg = make_config(supervisor_deadline=2.0, supervisor_poll=0.05,
                          interval=0.2)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            time.sleep(1.2)  # several supervision passes
            assert server.overload.supervisor.stall_counts == {}
        finally:
            server.shutdown()


class TestOverloadSoak:
    """The acceptance soak: 20 rounds at 30 % injected ingest faults
    (drop/truncate/duplicate) under a hard memory watermark. Pins:
    - shedding engages within one poll interval of crossing the hard
      watermark, and releases within one interval of pressure release;
    - counter deltas from every admitted packet are lossless;
    - every shed histogram sample is accounted for in ingest.shed_total.
    """

    COUNTERS_PER_ROUND = 50
    HISTOS_PER_ROUND = 20

    def _fault_deltas(self, chaos, before):
        after = dict(chaos.packet_faults)
        delta = {k: after.get(k, 0) - before.get(k, 0)
                 for k in ("drop", "truncate", "duplicate")}
        return after, delta

    @pytest.mark.slow
    def test_soak_20_rounds_30pct_ingest_faults_under_watermark(self):
        poll = 0.05
        cfg = make_config(
            chaos_enabled=True, chaos_seed=42,
            chaos_ingest_drop_rate=0.15,
            chaos_ingest_truncate_rate=0.10,
            chaos_ingest_duplicate_rate=0.05,   # 30 % total fault rate
            overload_watermark_soft_bytes=10**13,
            overload_watermark_hard_bytes=2 * 10**13,
            overload_watermark_poll=poll)
        sink = ChannelMetricSink()
        server = Server(cfg, extra_metric_sinks=[sink])
        server.start()
        chaos = server.chaos
        expected_counters = 0.0
        expected_histo_count = 0.0
        expected_shed = 0
        pf = dict(chaos.packet_faults)
        try:
            for rnd in range(20):
                if rnd == 8:
                    # cross the hard watermark: shedding must engage
                    # within one poll interval
                    chaos.set_simulated_rss(3 * 10**13)
                    assert wait_until(
                        lambda: server.overload.state == SHEDDING,
                        timeout=10 * poll + 1.0), \
                        "hard watermark exceeded for more than one interval"
                if rnd == 15:
                    # release: back to ok within one interval
                    chaos.set_simulated_rss(0)
                    assert wait_until(
                        lambda: server.overload.state == OK,
                        timeout=10 * poll + 1.0), \
                        "did not return to ok within one interval"
                state = server.overload.state
                server.handle_packet_batch(
                    [b"soak.c:1|c"] * self.COUNTERS_PER_ROUND)
                pf, d = self._fault_deltas(chaos, pf)
                # every truncation of b"soak.c:1|c" is a parse error, so
                # admitted = sent - dropped - truncated + duplicated
                expected_counters += (self.COUNTERS_PER_ROUND - d["drop"]
                                      - d["truncate"] + d["duplicate"])
                # single-char type on purpose: every possible truncation
                # of this packet is a parse error (b"...|m" would parse
                # as a valid timer), keeping the loss accounting exact
                server.handle_packet_batch(
                    [b"soak.h:1|h"] * self.HISTOS_PER_ROUND)
                pf, d = self._fault_deltas(chaos, pf)
                surviving = (self.HISTOS_PER_ROUND - d["drop"]
                             - d["truncate"] + d["duplicate"])
                if state == SHEDDING:
                    expected_shed += surviving
                else:
                    expected_histo_count += surviving
                server.flush()
            flushed = sink.drain()
            got = by_name(flushed)
            counter_total = sum(
                m.value for m in got.get("soak.c", []))
            assert counter_total == expected_counters, \
                "admitted counter deltas were not lossless"
            histo_count = sum(
                m.value for m in got.get("soak.h.count", []))
            assert histo_count == expected_histo_count
            shed = server.overload.shed_total.get("histogram|overload", 0)
            assert shed == expected_shed, \
                "shed histogram samples not fully accounted"
            # the ladder surfaced in /metrics and the flight recorder
            exposition = server.telemetry.registry.render_prometheus()
            assert "veneur_ingest_shed_total" in exposition
            assert "veneur_chaos_packet_faults_total" in exposition
            transitions = server.telemetry.events.snapshot(
                kind="overload_state")
            assert [e["new"] for e in transitions] == [SHEDDING, OK]
        finally:
            server.shutdown()


class TestOverloadManagerLifecycle:
    def test_monitor_thread_polls_watermarks(self):
        cfg = make_config(chaos_enabled=True,
                          overload_watermark_soft_bytes=10**13,
                          overload_watermark_hard_bytes=2 * 10**13,
                          overload_watermark_poll=0.05)
        mgr = OverloadManager(cfg, chaos=Chaos(ingest_rss_bytes=3 * 10**13))
        mgr.start()
        try:
            assert wait_until(lambda: mgr.state == SHEDDING, timeout=5.0)
        finally:
            mgr.stop()

    def test_oversized_span_batch_is_not_shed_forever(self):
        """A native SSF batch larger than one burst must still admit
        when the bucket is full — the ask clamps to capacity instead of
        turning the rate limit into a hard per-batch size cap."""
        mgr = OverloadManager(make_config(ingest_rate_limit_spans=100.0,
                                          ingest_rate_limit_burst=1.0))
        assert mgr.admit_spans(150)          # full bucket: clamped admit
        assert not mgr.admit_spans(150)      # drained: shed + counted
        assert mgr.shed_total.get("span|rate_limit") == 150

    def test_burst_knob_accepts_duration_strings(self):
        from veneur_tpu.config import read_config
        cfg = read_config(overrides={"ingest_rate_limit_burst": "500ms",
                                     "supervisor_deadline": "30s"})
        assert cfg.ingest_rate_limit_burst == 0.5
        assert cfg.supervisor_deadline == 30.0

    def test_telemetry_rows_shape(self):
        mgr = OverloadManager(make_config())
        mgr.shed(ov.CLASS_SPAN, 3, reason="rate_limit")
        rows = mgr.telemetry_rows()
        names = {r[0] for r in rows}
        assert {"overload.state", "overload.rss_bytes",
                "ingest.shed_total"} <= names
        assert ("ingest.shed_total", "counter", 3.0,
                ["class:span", "reason:rate_limit"]) in rows

    def test_stop_is_idempotent_and_threadless_by_default(self):
        mgr = OverloadManager(make_config())
        mgr.stop()
        mgr.stop()


class TestIngestDropCounters:
    """Satellite: the TCP over-long drop and undecodable SSF span drop
    are counted in server stats and surface in /metrics."""

    def test_tcp_overlong_line_is_counted(self):
        cfg = make_config(
            statsd_listen_addresses=["tcp://127.0.0.1:0"],
            metric_max_length=64)
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            addr = server.local_addr("tcp")
            with socket.create_connection(addr, timeout=5) as s:
                s.sendall(b"x" * 200)  # no newline: over-long buffer
                assert wait_until(
                    lambda: server.stats["tcp_overlong_dropped"] == 1)
            exposition = server.telemetry.registry.render_prometheus()
            assert "veneur_ingest_tcp_overlong_dropped_total 1" in exposition
        finally:
            server.shutdown()

    def test_undecodable_ssf_span_is_counted(self):
        cfg = make_config(ssf_listen_addresses=["tcp://127.0.0.1:0"])
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            addr = server.local_addr("ssf-tcp")
            import struct
            # valid frame (version 0 + length header), garbage protobuf
            # body: framing survives, decode fails -> counted drop
            body = b"\xff\xff\xff\xff\xff"
            frame = b"\x00" + struct.pack(">I", len(body)) + body
            with socket.create_connection(addr, timeout=5) as s:
                s.sendall(frame)
                assert wait_until(
                    lambda: server.stats["ssf_undecodable_dropped"] == 1)
            exposition = server.telemetry.registry.render_prometheus()
            assert ("veneur_ingest_ssf_undecodable_dropped_total 1"
                    in exposition)
        finally:
            server.shutdown()
