"""Forward-plane tests: local->global streaming, import merge kernels, and
distributed accuracy — without a cluster (pattern from reference
flusher_test.go:100-343 and internal/forwardtest)."""

import random
import time

import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.samplers.metrics import MetricType
from veneur_tpu.sinks.channel import ChannelMetricSink
from veneur_tpu.testing.forwardtest import ForwardTestServer


def make_config(**overrides) -> Config:
    cfg = Config()
    # tests flush manually; a real-sized interval keeps the forward
    # deadline (== interval) clear of first-compile latency
    cfg.interval = 10.0
    cfg.hostname = "test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


def _mk_meta(name):
    from veneur_tpu.core.columnstore import RowMeta
    from veneur_tpu.samplers.metrics import MetricScope
    return RowMeta(name=name, tags=[], joined_tags="", digest32=1,
                   scope=MetricScope.GLOBAL_ONLY, wire_type="counter")


def wait_until(fn, timeout=10.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


class TestForwardClient:
    def test_local_server_forwards_mergeable_state(self):
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        try:
            cfg = make_config(forward_address=ft.address)
            server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
            server.start()
            server.handle_metric_packet(b"fwd.gc:5|c|#veneurglobalonly")
            server.handle_metric_packet(b"fwd.local:9|c")  # mixed scope
            server.handle_metric_packet(b"fwd.gg:2.5|g|#veneurglobalonly")
            for v in (1, 2, 3):
                server.handle_metric_packet(b"fwd.lat:%d|ms" % v)
            for member in (b"a", b"b", b"c"):
                server.handle_metric_packet(b"fwd.users:%s|s" % member)
            server.flush()
            assert wait_until(lambda: len(received) >= 4)
            by_name = {p.name: p for p in received}
            assert by_name["fwd.gc"].counter.value == 5
            assert by_name["fwd.gc"].scope == metric_pb2.Global
            assert by_name["fwd.gg"].gauge.value == 2.5
            lat = by_name["fwd.lat"]
            assert lat.type == metric_pb2.Timer
            d = lat.histogram.t_digest
            assert sum(c.weight for c in d.main_centroids) == pytest.approx(3)
            assert d.min == 1 and d.max == 3
            # sets go out in the axiomhq binary format (dense, v1) so a
            # Go global veneur can UnmarshalBinary+Merge them
            from veneur_tpu.forward import hllwire
            regs, p = hllwire.unmarshal(by_name["fwd.users"].set.hyper_log_log)
            assert p == 14
            assert (regs > 0).sum() > 0
            # mixed counters are NOT forwarded; they flush locally
            assert "fwd.local" not in by_name
            server.shutdown()
        finally:
            ft.stop()

    def test_v1_fallback_to_v2_stream(self):
        """A V2-only importer (the reference contract,
        sources/proxy/server.go:138-142) answers the bulk V1 call with
        UNIMPLEMENTED; the client must pin to V2 and deliver the SAME
        flush, not drop it."""
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.client import ForwardClient

        received = []
        ft = ForwardTestServer(received.extend)  # implements only V2
        ft.start()
        try:
            client = ForwardClient(ft.address, deadline=10.0)
            assert client._v1_ok is True
            fwd = ForwardableState()
            meta = _mk_meta("fb.count")
            fwd.counters.append((meta, 4.0))
            assert client.forward(fwd) == 1
            assert client._v1_ok is False      # pinned after refusal
            assert client.forward(fwd) == 1    # subsequent direct V2
            assert wait_until(lambda: len(received) == 2)
            assert received[0].counter.value == 4
            assert not any(v for k, v in client.stats.items()
                           if k.startswith("errors"))
            client.close()
        finally:
            ft.stop()

    def test_v1_bulk_path_against_import_server(self):
        """Against this framework's importer the first V1 call sticks
        (one unary MetricList instead of 50k stream messages)."""
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.forward.server import ImportServer

        gcfg = make_config()
        gserver = Server(gcfg, extra_metric_sinks=[ChannelMetricSink()])
        imp = ImportServer(gserver, "127.0.0.1:0")
        imp.start()
        try:
            client = ForwardClient(imp.address, deadline=10.0)
            fwd = ForwardableState()
            fwd.counters.append((_mk_meta("v1.count"), 11.0))
            assert client.forward(fwd) == 1
            assert client._v1_ok is True
            assert wait_until(lambda: imp.imported_total == 1)
            assert imp.rpc_stats.snapshot()["SendMetrics"]["count"] >= 1
            client.close()
        finally:
            imp.stop()
            gserver.shutdown()

    def test_forward_bad_address_does_not_crash(self):
        cfg = make_config(forward_address="127.0.0.1:1")  # nothing listens
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        server.handle_metric_packet(b"x:1|h")
        server.flush()  # must not raise
        assert server.forward_client.stats["errors_unavailable"] >= 1 or \
            server.forward_client.stats["errors_send"] >= 1 or \
            server.forward_client.stats["errors_deadline"] >= 1
        server.shutdown()


class TestLocalGlobalEndToEnd:
    def _spawn_global(self):
        gcfg = make_config(grpc_address="127.0.0.1:0")
        g_obs = ChannelMetricSink()
        gserver = Server(gcfg, extra_metric_sinks=[g_obs])
        gserver.start()
        return gserver, g_obs

    def _spawn_local(self, global_addr):
        lcfg = make_config(forward_address=global_addr)
        l_obs = ChannelMetricSink()
        lserver = Server(lcfg, extra_metric_sinks=[l_obs])
        lserver.start()
        return lserver, l_obs

    def test_histogram_percentiles_merge_globally(self):
        gserver, g_obs = self._spawn_global()
        l1, _ = self._spawn_local(gserver.import_server.address)
        l2, _ = self._spawn_local(gserver.import_server.address)
        try:
            rng = random.Random(3)
            data = [rng.normalvariate(100, 15) for _ in range(2000)]
            for i, v in enumerate(data):
                (l1 if i % 2 else l2).handle_metric_packet(
                    b"e2e.lat:%.4f|h" % v)
            l1.flush()
            l2.flush()
            assert wait_until(
                lambda: gserver.import_server.imported_total >= 2)
            gserver.flush()
            got = {}
            for metric in g_obs.wait_flush(timeout=5):
                got[metric.name] = metric
            data.sort()
            for p in (50, 75, 99):
                want = data[int(len(data) * p / 100)]
                assert got[f"e2e.lat.{p}percentile"].value == pytest.approx(
                    want, rel=0.03), p
            # global server emits no count for mixed histos merged from
            # locals (local-stat guards), but each local emitted its own
        finally:
            l1.shutdown()
            l2.shutdown()
            gserver.shutdown()

    def test_global_counters_and_sets_merge(self):
        gserver, g_obs = self._spawn_global()
        l1, _ = self._spawn_local(gserver.import_server.address)
        l2, _ = self._spawn_local(gserver.import_server.address)
        try:
            l1.handle_metric_packet(b"e2e.gc:5|c|#veneurglobalonly")
            l2.handle_metric_packet(b"e2e.gc:7|c|#veneurglobalonly")
            for i in range(300):
                l1.handle_metric_packet(b"e2e.uniq:u%d|s" % i)
            for i in range(150, 450):
                l2.handle_metric_packet(b"e2e.uniq:u%d|s" % i)
            l1.flush()
            l2.flush()
            assert wait_until(
                lambda: gserver.import_server.imported_total >= 4)
            gserver.flush()
            got = {}
            for metric in g_obs.wait_flush(timeout=5):
                got[metric.name] = metric
            # counter merge = addition across locals
            assert got["e2e.gc"].value == 12.0
            assert got["e2e.gc"].type == MetricType.COUNTER
            # HLL register-max merge: 450 distinct members, 150 overlapping
            assert got["e2e.uniq"].value == pytest.approx(450, rel=0.05)
        finally:
            l1.shutdown()
            l2.shutdown()
            gserver.shutdown()

    def test_import_rejects_nothing_but_still_counts(self):
        gserver, _ = self._spawn_global()
        try:
            from veneur_tpu.forward.client import ForwardClient
            from veneur_tpu.forward.protos import tdigest_pb2

            client = ForwardClient(gserver.import_server.address)
            pbm = metric_pb2.Metric(
                name="direct.histo", tags=["a:b"], type=metric_pb2.Histogram,
                scope=metric_pb2.Mixed,
                histogram=metric_pb2.HistogramValue(
                    t_digest=tdigest_pb2.MergingDigestData(
                        compression=100.0, min=1.0, max=9.0,
                        main_centroids=[
                            tdigest_pb2.Centroid(mean=1.0, weight=2.0),
                            tdigest_pb2.Centroid(mean=9.0, weight=2.0),
                        ])))
            client._send_v2(iter([pbm]), timeout=5)
            assert wait_until(
                lambda: gserver.import_server.imported_total >= 1)
            out, export, touched, meta = \
                gserver.store.histos.snapshot_and_reset((0.5,))
            assert touched[0]
            assert float(out["count"][0]) == pytest.approx(4.0)
            assert float(out["min"][0]) == 1.0
            assert float(out["max"][0]) == 9.0
            client.close()
        finally:
            gserver.shutdown()


class TestForwardOnly:
    def test_forward_only_promotes_default_scope(self):
        """forward_only makes undeclared-scope metrics global-only, so a
        local server forwards everything and flushes nothing for them
        (reference server.go:547-552, worker.go:353-354)."""
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        try:
            cfg = make_config(forward_address=ft.address, forward_only=True)
            sink = ChannelMetricSink()
            server = Server(cfg, extra_metric_sinks=[sink])
            server.start()
            server.handle_metric_packet(b"fo.plain:7|c")  # no scope tag
            server.handle_metric_packet(b"fo.pinned:1|c|#veneurlocalonly")
            server.flush()
            assert wait_until(lambda: len(received) >= 1)
            by = {p.name: p for p in received}
            assert by["fo.plain"].counter.value == 7
            assert by["fo.plain"].scope == metric_pb2.Global
            # an explicit local pin still beats the forward_only default
            assert "fo.pinned" not in by
            local = {m.name for m in sink.drain()}
            assert "fo.pinned" in local
            assert "fo.plain" not in local
            server.shutdown()
        finally:
            ft.stop()
