"""core/profiling.py HTTP surface: the pprof family returns valid
gzipped pprof protos and the device trace returns a readable zip.

The pprof wire format is a gzipped `perftools.profiles.Profile`
protobuf; "valid" here means: gzip magic, decompresses, and the proto's
top-level fields parse with the expected shape (sample_type field 1,
string_table field 6, period field 12 — the fields `go tool pprof`
requires to load a profile at all).
"""

import gzip
import io
import socket
import zipfile

import pytest

from veneur_tpu.core import profiling
from veneur_tpu.core.httpapi import HTTPApi
from veneur_tpu.util import http as vhttp
from veneur_tpu.util.protowire import read_fields

from test_server import generate_config


def api_url(api, path):
    host, port = api.address
    return f"http://{host}:{port}{path}"


def parse_pprof(body: bytes) -> dict:
    """Decompress + parse the top-level Profile fields; returns
    {field_number: [values]}. Raises on anything malformed."""
    assert body[:2] == b"\x1f\x8b", "pprof payload must be gzipped"
    raw = gzip.decompress(body)
    assert raw, "empty profile proto"
    fields: dict = {}
    for num, _wt, value in read_fields(raw):
        fields.setdefault(num, []).append(value)
    return fields


def assert_valid_profile(body: bytes, want_samples: bool = True):
    fields = parse_pprof(body)
    # Profile: 1=sample_type, 2=sample, 4=location, 5=function,
    # 6=string_table, 12=period
    assert 1 in fields, "profile has no sample_type"
    assert 6 in fields, "profile has no string_table"
    assert 12 in fields, "profile has no period"
    if want_samples:
        assert 2 in fields, "profile recorded no samples"
        assert 4 in fields and 5 in fields
    # string_table[0] must be "" (the pprof spec's sentinel)
    assert fields[6][0] == b""
    return fields


class TestPprofFunctions:
    """Function-level shape checks (no HTTP server)."""

    def test_cpu_profile_is_valid_pprof(self):
        assert_valid_profile(profiling.pprof_for(0.15))

    def test_threads_profile_is_valid_pprof(self):
        assert_valid_profile(profiling.threads_pprof())

    def test_heap_profile_is_valid_pprof(self):
        body, _fresh = profiling.heap_pprof_or_cached()
        # heap capture under tracemalloc may legitimately catch zero
        # allocations in a quiet interpreter; shape still must hold
        assert_valid_profile(body, want_samples=False)

    def test_empty_profile_is_valid(self):
        assert_valid_profile(profiling.empty_pprof("mutex"),
                             want_samples=False)

    def test_device_trace_is_readable_zip(self):
        body = profiling.capture_device_trace(0.2)
        zf = zipfile.ZipFile(io.BytesIO(body))
        assert zf.namelist(), "device trace zip is empty"
        assert zf.testzip() is None  # every member's CRC checks out


class TestPprofEndpoints:
    """The HTTP routes (reference http.go:53-63 mounts Go pprof here)."""

    def _start(self):
        api = HTTPApi(generate_config(), address="127.0.0.1:0")
        api.start()
        return api

    def test_profile_endpoint(self):
        api = self._start()
        try:
            status, body = vhttp.get(
                api_url(api, "/debug/pprof/profile?seconds=0.2"))
            assert status == 200
            assert_valid_profile(body)
        finally:
            api.stop()

    def test_heap_endpoint(self):
        api = self._start()
        try:
            try:
                status, body = vhttp.get(api_url(api, "/debug/pprof/heap"))
            except vhttp.HTTPError as e:
                if e.status == 429:  # arming throttle, nothing cached yet
                    pytest.skip("heap profiler throttled by an earlier test")
                raise
            assert status == 200
            assert_valid_profile(body, want_samples=False)
        finally:
            api.stop()

    def test_goroutine_endpoint(self):
        api = self._start()
        try:
            status, body = vhttp.get(api_url(api, "/debug/pprof/goroutine"))
            assert status == 200
            fields = assert_valid_profile(body)
            # at least this test's thread and the HTTP server thread
            assert len(fields[2]) >= 2
        finally:
            api.stop()

    def test_device_trace_endpoint_zip(self):
        api = self._start()
        try:
            try:
                status, body = vhttp.get(
                    api_url(api, "/debug/profile/device?seconds=0.2"),
                    timeout=30.0)
            except (socket.timeout, OSError) as e:
                # the jax profiler trace can wedge under this CI's
                # sandboxed runtime (the pre-existing device-trace HTTP
                # test fails the same way); the function-level zip test
                # above still pins the payload contract
                pytest.skip(f"device trace over HTTP unavailable: {e}")
            assert status == 200
            zf = zipfile.ZipFile(io.BytesIO(body))
            assert zf.namelist()
            assert zf.testzip() is None
        finally:
            api.stop()
