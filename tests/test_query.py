"""Live query plane + alert engine (the `query` marker).

The consistency contract under pin: a `/query` taken between flushes
returns values BIT-IDENTICAL to evaluating the same readout kernels on
the subsequent flush's captured generation restricted to the same rows
— single-device AND mesh, under `flush_async: true`, across a
capacity-resize boundary, and with concurrent ingest to other rows.
`ledger_strict` stays green throughout (a query moves no samples, so it
must not perturb conservation).

The alert engine's state machines (idle -> pending -> firing ->
resolved with `for:` hold-down), flight-recorder `alert_transition`
events, log rate limiting, and SIGHUP-shaped hot reload are pinned
here too, plus the HTTP surface (/query, /alerts, ?kind= event
filtering, http.route.* rows).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.query import QueryError, QuerySpec, parse_tags
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink

pytestmark = pytest.mark.query


def wait_until(fn, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def corpus(round_no: int = 0):
    lines = []
    for i in range(8):
        lines.append(b"c.%d:%d|c|#env:t" % (i, i + 1 + round_no))
        lines.append(b"g.%d:%.2f|g" % (i, i * 1.5 + round_no))
        lines.append(b"t.%d:%.2f|ms" % (i, 10.0 + i + round_no))
        lines.append(b"t.%d:%.2f|ms" % (i, 40.0 + i))
        lines.append(b"s.%d:m%d|s" % (i, i))
        lines.append(b"s.%d:m%d|s" % (i, i + 50 + round_no))
        lines.append(b"ll.%d:%.2f|l" % (i, 3.0 + i + round_no))
    return lines


def mk_server(**kw):
    cfg = Config()
    cfg.interval = 60.0
    cfg.hostname = "test"
    cfg.statsd_listen_addresses = []
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    cfg.ledger_strict = True
    for k, v in kw.items():
        if "." in k:
            ns, field = k.split(".", 1)
            setattr(getattr(cfg, ns), field, v)
        else:
            setattr(cfg, k, v)
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[obs]), obs


def _feed(server, lines):
    for line in lines:
        server.handle_metric_packet(line)
    server.store.apply_all_pending()


def _q(server, metric, kind, **kw):
    return server.query_plane.query(
        QuerySpec.build(metric=metric, kind=kind, **kw))


def _flushed(metrics):
    """{(name, sorted tags): value} for exact-equality lookups."""
    return {(m.name, tuple(sorted(m.tags))): float(m.value)
            for m in metrics}


def _assert_queries_match_flush(queries: dict, flushed: dict):
    """The pin itself: every pre-flush query value equals (==, not
    approx — the kernels are the same, so the floats must be the same
    bits) the next flush's reading of the same row."""
    for label, (fname, ftags, qval) in queries.items():
        assert (fname, ftags) in flushed, \
            f"{label}: {fname}{ftags} missing from flush output"
        got = flushed[(fname, ftags)]
        assert qval == got, f"{label}: query {qval!r} != flush {got!r}"


def _query_all(server):
    """One query per family against the fixed corpus; returns
    {label: (flush_name, flush_tags, query_value)} for the pin."""
    return {
        "t50": ("t.0.50percentile", (),
                _q(server, "t.0", "quantile", q=0.5)["value"]),
        "t99": ("t.0.99percentile", (),
                _q(server, "t.0", "quantile", q=0.99)["value"]),
        "ll50": ("ll.0.50percentile", (),
                 _q(server, "ll.0", "quantile", q=0.5)["value"]),
        "count": ("c.0", ("env:t",),
                  _q(server, "c.0", "count",
                     tags=parse_tags("env:t"))["value"]),
        "gauge": ("g.0", (), _q(server, "g.0", "value")["value"]),
        "card": ("s.0", (), _q(server, "s.0", "cardinality")["value"]),
    }


class TestQueryConsistency:
    def test_query_matches_next_flush_single_device(self):
        """The base pin: queries between flushes == the next flush's
        readout of the same generation, all five families, exact."""
        server, obs = mk_server()
        try:
            _feed(server, corpus())
            queries = _query_all(server)
            # staleness is surfaced, and zero once pending is applied
            r = _q(server, "c.0", "count", tags=parse_tags("env:t"))
            assert r["stale_pending_samples"] == 0
            assert r["matched_rows"] == 1
            server.flush()  # ledger_strict: raises on any perturbation
            _assert_queries_match_flush(queries, _flushed(obs.drain()))
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    @pytest.mark.mesh
    def test_query_matches_next_flush_on_mesh(self):
        """Same pin over the sharded mesh store: the query path runs
        the NON-reset collective merges, which must reduce with the
        exact same expressions as the flush's fused donating merges."""
        server, obs = mk_server(**{"tpu.shards": 2})
        assert server.store.shard_plane is not None, "virtual mesh missing"
        try:
            _feed(server, corpus())
            queries = _query_all(server)
            server.flush()
            _assert_queries_match_flush(queries, _flushed(obs.drain()))
            # and the query left the live mesh state intact: a second
            # interval ingests + flushes cleanly (ledger_strict)
            _feed(server, corpus(round_no=3))
            _query_all(server)
            server.flush()
            assert obs.drain()
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_query_under_flush_async(self):
        """With the overlapped flush on, a query between flushes matches
        the interval's eventual DELIVERED readout (tick 2), and a query
        right after the swap sees the fresh (empty) generation."""
        server, obs = mk_server(flush_async=True)
        try:
            _feed(server, corpus())
            queries = _query_all(server)
            server.flush()  # tick 1: swap + submit, no delivery
            assert obs.drain() == []
            # post-swap, the live generation is fresh: nothing matches
            r = _q(server, "t.0", "quantile", q=0.5)
            assert r["matched_rows"] == 0 and r["value"] is None
            wait_until(
                lambda: server._inflight_flushes[0]["pending"].done())
            server.flush()  # tick 2: joins + delivers interval 1
            _assert_queries_match_flush(queries, _flushed(obs.drain()))
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_query_across_resize_boundary(self):
        """Growing a family past its capacity rung mid-interval must
        leave the query plane consistent: queries after the resize
        match the next flush over the resized generation."""
        server, obs = mk_server(**{"tpu.histo_capacity": 32})
        try:
            _feed(server, corpus())
            before = _q(server, "t.0", "quantile", q=0.5)["value"]
            # blow through the 32-row rung with distinct histo keys
            _feed(server, [b"resize.%d:%d|ms" % (i, i)
                           for i in range(64)])
            assert server.store.histos.capacity > 32
            after = _q(server, "t.0", "quantile", q=0.5)
            # t.0 saw no new samples: the resize itself must not move it
            assert after["value"] == before
            queries = _query_all(server)
            server.flush()
            _assert_queries_match_flush(queries, _flushed(obs.drain()))
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_query_with_concurrent_ingest(self):
        """Readers race ingest to OTHER rows: queries stay exact for
        the rows they match (the capture is consistent), and the final
        pre-flush values still equal the flush readout."""
        server, obs = mk_server()
        try:
            _feed(server, corpus())
            stop = threading.Event()
            errors = []

            def _ingest():
                i = 0
                while not stop.is_set():
                    server.handle_metric_packet(
                        b"other.%d:1|c" % (i % 16))
                    i += 1

            def _read():
                while not stop.is_set():
                    try:
                        _q(server, "t.0", "quantile", q=0.5)
                        _q(server, "c.0", "count",
                           tags=parse_tags("env:t"))
                    except Exception as e:  # pragma: no cover
                        errors.append(e)
                        return

            threads = [threading.Thread(target=_ingest)] + \
                [threading.Thread(target=_read) for _ in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.8)
            stop.set()
            for t in threads:
                t.join(5.0)
                assert not t.is_alive()
            assert not errors
            server.store.apply_all_pending()
            queries = _query_all(server)
            server.flush()  # ledger_strict: concurrent reads cost nothing
            _assert_queries_match_flush(queries, _flushed(obs.drain()))
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_tag_filter_and_errors(self):
        server, obs = mk_server()
        try:
            _feed(server, [b"m:1|c|#env:prod,svc:a", b"m:2|c|#env:dev"])
            prod = _q(server, "m", "count", tags=parse_tags("env:prod"))
            assert prod["matched_rows"] == 1 and prod["value"] == 1.0
            both = _q(server, "m", "count")
            assert both["matched_rows"] == 2 and both["value"] == 3.0
            with pytest.raises(QueryError):
                QuerySpec.build(metric="", kind="count")
            with pytest.raises(QueryError):
                QuerySpec.build(metric="m", kind="nope")
            with pytest.raises(QueryError):
                QuerySpec.build(metric="m", kind="quantile")  # no q
            with pytest.raises(QueryError):
                QuerySpec.build(metric="m", kind="bin_occupancy",
                                lo=2.0, hi=1.0)
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()


class TestAlertEngine:
    def test_lifecycle_pending_firing_resolved(self):
        """The full state machine with a `for:` hold-down, plus the
        flight-recorder trail: every transition is an alert_transition
        event stamped with the interval trace id."""
        server, obs = mk_server()
        try:
            _feed(server, corpus())
            server.alerts.configure([
                {"id": "hits", "metric": "c.0", "kind": "count",
                 "op": ">", "threshold": 0.5, "for": "0.2s",
                 "tags": "env:t"},
            ])
            now = time.time()
            trs = server.alerts.evaluate_once(now=now)
            assert [(t["from_state"], t["to_state"]) for t in trs] == \
                [("idle", "pending")]
            # hold-down not yet satisfied
            assert server.alerts.evaluate_once(now=now + 0.1) == []
            trs = server.alerts.evaluate_once(now=now + 0.3)
            assert [(t["from_state"], t["to_state"]) for t in trs] == \
                [("pending", "firing")]
            rep = server.alerts.report()
            assert rep["rules"][0]["state"] == "firing"
            assert rep["rules"][0]["value"] == 1.0
            server.flush()  # resets the counter generation
            trs = server.alerts.evaluate_once(now=now + 0.5)
            assert [(t["from_state"], t["to_state"]) for t in trs] == \
                [("firing", "resolved")]
            events = server.telemetry.events.snapshot(
                kind="alert_transition")
            assert [e["to_state"] for e in events] == \
                ["pending", "firing", "resolved"]
            assert all(e["rule"] == "hits" for e in events)
            assert all(e.get("trace_id") for e in events)
            # state machine rows export
            rows = {r[0] for r in server.alerts.telemetry_rows()}
            assert {"alert.rules", "alert.state", "alert.firing",
                    "alert.evals_total",
                    "alert.transitions_total"} <= rows
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_hot_reload_preserves_surviving_state(self):
        server, obs = mk_server()
        try:
            _feed(server, corpus())
            server.alerts.configure([
                {"id": "a", "metric": "c.0", "kind": "count",
                 "op": ">", "threshold": 0.0, "tags": "env:t"},
                {"id": "b", "metric": "g.0", "kind": "value",
                 "op": ">", "threshold": 1e9},
            ])
            server.alerts.evaluate_once()
            assert server.alerts.report()["rules"][0]["state"] == "firing"
            # reload: keep `a`, drop `b`, add `c` — a's firing survives
            n = server.alerts.configure([
                {"id": "a", "metric": "c.0", "kind": "count",
                 "op": ">", "threshold": 0.0, "tags": "env:t"},
                {"id": "c", "metric": "s.0", "kind": "cardinality",
                 "op": ">=", "threshold": 1.0},
            ])
            assert n == 2
            rep = {r["id"]: r for r in server.alerts.report()["rules"]}
            assert rep["a"]["state"] == "firing"
            assert rep["c"]["state"] == "idle"
            assert "b" not in rep
            # a bad reload raises and keeps the table
            with pytest.raises(QueryError):
                server.alerts.configure([{"id": "x", "metric": "m",
                                          "kind": "count", "op": "~",
                                          "threshold": 1}])
            assert {r["id"] for r in
                    server.alerts.report()["rules"]} == {"a", "c"}
            # the server-level reload path records the event
            server.reload_alerts()
            assert server.telemetry.events.snapshot(kind="alerts_reload")
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_transition_log_rate_limit(self):
        """First transition per rule per flush interval is logged, the
        rest within the same interval only count (events still land)."""
        server, obs = mk_server()
        try:
            _feed(server, corpus())
            server.alerts.configure([
                {"id": "flap", "metric": "c.0", "kind": "count",
                 "op": ">", "threshold": 0.5, "tags": "env:t"},
            ])
            now = time.time()
            server.alerts.evaluate_once(now=now)        # -> firing
            # force a clear without a flush: flap the threshold via a
            # reload (state survives, threshold now unreachable)
            server.alerts.configure([
                {"id": "flap", "metric": "c.0", "kind": "count",
                 "op": ">", "threshold": 1e9, "tags": "env:t"},
            ])
            server.alerts.evaluate_once(now=now + 0.1)  # -> resolved
            assert server.alerts.suppressed_logs_total == 1
            events = server.telemetry.events.snapshot(
                kind="alert_transition")
            assert len(events) == 2  # the recorder is never suppressed
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_config_block_and_rule_validation(self):
        from veneur_tpu.config import AlertsConfig
        cfg = AlertsConfig(interval="500ms", rules=[
            {"id": "r1", "metric": "m", "kind": "quantile", "q": 0.99,
             "op": ">", "threshold": 100, "for": "30s"}])
        assert cfg.interval == 0.5
        server, obs = mk_server()
        try:
            n = server.alerts.configure(cfg.rules, interval_s=cfg.interval)
            assert n == 1 and server.alerts.interval_s == 0.5
            rule = server.alerts.report()["rules"][0]
            assert rule["for_s"] == 30.0 and rule["q"] == 0.99
            with pytest.raises(QueryError):  # duplicate ids
                server.alerts.configure([
                    {"id": "d", "metric": "m", "kind": "count",
                     "threshold": 1},
                    {"id": "d", "metric": "m2", "kind": "count",
                     "threshold": 1}])
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()


class TestHTTPSurface:
    def test_query_alerts_routes_and_route_latency(self):
        from veneur_tpu.core.httpapi import HTTPApi
        server, obs = mk_server()
        api = None
        try:
            _feed(server, corpus())
            server.alerts.configure([
                {"id": "hits", "metric": "c.0", "kind": "count",
                 "op": ">", "threshold": 0.5, "tags": "env:t"}])
            server.alerts.evaluate_once()
            api = HTTPApi(server.config, server=server,
                          address="127.0.0.1:0")
            api.start()
            host, port = api.address

            def get(path):
                try:
                    with urllib.request.urlopen(
                            f"http://{host}:{port}{path}", timeout=10) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            status, body = get("/query?metric=c.0&kind=count&tags=env:t")
            assert status == 200
            payload = json.loads(body)
            assert payload["value"] == 1.0
            assert payload["kind"] == "count"
            status, body = get(
                "/query?metric=t.0&kind=percentile&q=0.5")
            assert status == 200 and json.loads(body)["value"] is not None
            status, body = get("/query?kind=count")  # no metric
            assert status == 400 and b"metric" in body
            status, body = get("/alerts")
            assert status == 200
            rep = json.loads(body)
            assert rep["rules"][0]["id"] == "hits"
            assert rep["rules"][0]["state"] == "firing"
            # ?kind= filtering on the flight recorder
            status, body = get("/debug/events?kind=alert_transition")
            assert status == 200
            events = json.loads(body)["events"]
            assert events and all(e["kind"] == "alert_transition"
                                  for e in events)
            # every route above landed in the per-route llhists
            status, body = get("/metrics")
            assert status == 200
            text = body.decode()  # prometheus-mangled names
            assert "veneur_http_route_count_total" in text
            assert 'path="/query"' in text
            assert "veneur_query_requests_total" in text
            assert "veneur_alert_rules" in text
        finally:
            if api is not None:
                api.stop()
            server.config.flush_on_shutdown = False
            server.shutdown()


@pytest.mark.slow
class TestOverheadSoak:
    def test_alert_and_reader_overhead_bounded(self):
        """The acceptance soak: a 1 Hz alert evaluation over 64 rules
        plus 8 concurrent /query readers must cost <2% of flush wall
        time and leave flush.critical_path_s p99 unmoved (flush_async,
        the PR-15 overlap shape)."""
        server, obs = mk_server(flush_async=True)
        try:
            rules = []
            for i in range(8):
                rules += [
                    {"id": f"c{i}", "metric": f"c.{i}", "kind": "count",
                     "op": ">", "threshold": 1e9, "tags": "env:t"},
                    {"id": f"r{i}", "metric": f"c.{i}", "kind": "rate",
                     "op": ">", "threshold": 1e9, "tags": "env:t"},
                    {"id": f"g{i}", "metric": f"g.{i}", "kind": "value",
                     "op": ">", "threshold": 1e9},
                    {"id": f"t{i}", "metric": f"t.{i}",
                     "kind": "quantile", "q": 0.99, "op": ">",
                     "threshold": 1e9},
                    {"id": f"l{i}", "metric": f"ll.{i}",
                     "kind": "quantile", "q": 0.5, "op": ">",
                     "threshold": 1e9},
                    {"id": f"s{i}", "metric": f"s.{i}",
                     "kind": "cardinality", "op": ">", "threshold": 1e9},
                    {"id": f"b{i}", "metric": f"ll.{i}",
                     "kind": "bin_occupancy", "lo": 0.0, "hi": 100.0,
                     "op": ">", "threshold": 2.0},
                    {"id": f"q{i}", "metric": f"t.{i}",
                     "kind": "quantile", "q": 0.5, "op": ">",
                     "threshold": 1e9},
                ]
            assert len(rules) == 64
            server.alerts.configure(rules, interval_s=1.0)

            def flush_round(n, round0):
                walls, crits = [], []
                for k in range(n):
                    _feed(server, corpus(round_no=round0 + k))
                    t0 = time.perf_counter()
                    server.flush()
                    walls.append(time.perf_counter() - t0)
                for ri in server.telemetry.flushes.snapshot():
                    cp = ri.get("phases", {}).get("critical_path_s")
                    if cp is not None:
                        crits.append(float(cp))
                return walls, crits

            # warmup (kernel compiles must not pollute either side)
            flush_round(2, 0)
            base_walls, base_crits = flush_round(6, 10)

            stop = threading.Event()
            errors = []

            def _reader():
                while not stop.is_set():
                    try:
                        _q(server, "t.0", "quantile", q=0.5)
                    except QueryError:
                        pass  # post-swap empty generation: fine
                    except Exception as e:  # pragma: no cover
                        errors.append(e)
                        return
                    time.sleep(0.01)

            def _alert_tick():
                while not stop.is_set():
                    try:
                        server.alerts.evaluate_once()
                    except Exception:
                        pass
                    stop.wait(1.0)  # the 1 Hz cadence under test

            threads = [threading.Thread(target=_reader)
                       for _ in range(8)]
            threads.append(threading.Thread(target=_alert_tick))
            for t in threads:
                t.start()
            try:
                loaded_walls, loaded_crits = flush_round(6, 30)
            finally:
                stop.set()
                for t in threads:
                    t.join(10.0)
                    assert not t.is_alive()
            assert not errors

            base = float(np.mean(base_walls))
            loaded = float(np.mean(loaded_walls))
            # <2% of flush wall, with an absolute floor for CI jitter
            assert loaded - base <= 0.02 * base + 0.25, \
                f"flush wall moved: base={base:.3f}s loaded={loaded:.3f}s"
            if base_crits and loaded_crits:
                bp99 = float(np.percentile(base_crits, 99))
                lp99 = float(np.percentile(loaded_crits, 99))
                assert lp99 <= bp99 * 1.02 + 0.25, \
                    f"critical_path p99 moved: {bp99:.3f} -> {lp99:.3f}"
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()
