"""Columnar-flush parity: flush_columnstore_batch must emit exactly the
metrics (and forwardable state) the per-row flush_columnstore oracle
does, across every scope/type/server-mode combination. The legacy
per-row path stays as the readable spec (value-selection parity with
reference samplers.go:359-514); the batch path is what the server runs
(core/server.py flush), so these tests are the contract between them."""

from __future__ import annotations

import numpy as np
import pytest

from veneur_tpu.core.columnstore import ColumnStore
from veneur_tpu.core.flusher import (
    FlushBatch, flush_columnstore, flush_columnstore_batch)
from veneur_tpu.samplers.metrics import HistogramAggregates
from veneur_tpu.samplers.parser import Parser

PCTS = (0.5, 0.9, 0.99)
AGGS = HistogramAggregates.from_names(
    ["min", "max", "median", "avg", "count", "sum", "hmean"])


def _mk_store():
    return ColumnStore(counter_capacity=64, gauge_capacity=64,
                       histo_capacity=64, set_capacity=32, batch_cap=128)


def _feed(store, lines):
    p = Parser()
    for line in lines:
        p.parse_metric_fast(line, store.process)
    store.apply_all_pending()


def _mixed_corpus():
    lines = []
    for i in range(6):
        lines.append(b"c.%d:%d|c|#env:t,i:%d" % (i, i + 1, i))
        lines.append(b"g.%d:%.2f|g|#env:t" % (i, i * 1.5))
        lines.append(b"t.%d:%.2f|ms|#env:t" % (i, 10.0 + i))
        lines.append(b"t.%d:%.2f|ms|#env:t" % (i, 20.0 + i))
        lines.append(b"s.%d:user%d|s|#env:t" % (i, i))
        lines.append(b"s.%d:user%d|s|#env:t" % (i, i + 100))
        lines.append(b"ll.%d:%.2f|l|#env:t" % (i, 5.0 + i))
        lines.append(b"ll.%d:%.2f|l|#env:t" % (i, 50.0 + i))
    # explicit scope variants (veneurlocalonly / veneurglobalonly)
    lines += [
        b"lc:5|c|#veneurlocalonly",
        b"gc:7|c|#veneurglobalonly",
        b"lg:1.5|g|#veneurlocalonly",
        b"gg:2.5|g|#veneurglobalonly",
        b"lt:3.25|ms|#veneurlocalonly",
        b"lt:4.25|ms|#veneurlocalonly",
        b"gt:5.5|ms|#veneurglobalonly",
        b"ls:a|s|#veneurlocalonly",
        b"gs:b|s|#veneurglobalonly",
        b"lll:7.5|l|#veneurlocalonly",
        b"gll:8.5|l|#veneurglobalonly",
        b"sc.ok:0|sc|#veneurlocalonly",
    ]
    return lines


def _metric_key(m):
    return (m.name, round(float(m.value), 6), tuple(sorted(m.tags)),
            int(m.type), m.message, m.hostname)


def _flush_pair(is_local, collect_forward, lines=None):
    lines = lines if lines is not None else _mixed_corpus()
    legacy_store, batch_store = _mk_store(), _mk_store()
    _feed(legacy_store, lines)
    _feed(batch_store, lines)
    final, fwd_legacy = flush_columnstore(
        legacy_store, is_local, PCTS, AGGS, collect_forward=collect_forward)
    batch, fwd_batch = flush_columnstore_batch(
        batch_store, is_local, PCTS, AGGS, collect_forward=collect_forward)
    return final, fwd_legacy, batch, fwd_batch


@pytest.mark.parametrize("is_local", [False, True])
@pytest.mark.parametrize("collect_forward", [True, False])
def test_batch_matches_legacy(is_local, collect_forward):
    final, fwd_l, batch, fwd_b = _flush_pair(is_local, collect_forward)
    assert isinstance(batch, FlushBatch)
    assert len(batch) == len(final)
    got = sorted(_metric_key(m) for m in batch.materialize())
    want = sorted(_metric_key(m) for m in final)
    assert got == want

    # forwardable state parity
    def names_vals(lst):
        return sorted((meta.name, round(float(v), 6)) for meta, v in lst)
    assert names_vals(fwd_b.counters) == names_vals(fwd_l.counters)
    assert names_vals(fwd_b.gauges) == names_vals(fwd_l.gauges)
    hb = {h[0].name: h[1:] for h in fwd_b.histograms}
    hl = {h[0].name: h[1:] for h in fwd_l.histograms}
    assert hb.keys() == hl.keys()
    for k in hb:
        for a, b in zip(hb[k], hl[k]):
            np.testing.assert_allclose(a, b)
    sb = {s[0].name: s[1] for s in fwd_b.sets}
    sl = {s[0].name: s[1] for s in fwd_l.sets}
    assert sb.keys() == sl.keys()
    for k in sb:
        np.testing.assert_array_equal(sb[k], sl[k])


def test_batch_second_flush_uses_cached_names():
    store = _mk_store()
    lines = _mixed_corpus()
    _feed(store, lines)
    b1, _ = flush_columnstore_batch(store, False, PCTS, AGGS)
    first = sorted(_metric_key(m) for m in b1.materialize())
    _feed(store, lines)
    b2, _ = flush_columnstore_batch(store, False, PCTS, AGGS)
    second = sorted(_metric_key(m) for m in b2.materialize())
    assert first == second  # identical corpus -> identical names/tags


def test_name_cache_invalidated_on_row_recycle():
    """A recycled+re-interned row must not leak the previous occupant's
    cached flush name."""
    store = _mk_store()
    p = Parser()
    p.parse_metric_fast(b"old.key:1|c", store.process)
    store.apply_all_pending()
    batch, _ = flush_columnstore_batch(store, False, PCTS, AGGS)
    assert [m.name for m in batch.materialize()] == ["old.key"]
    # idle long enough to tombstone, then recycle
    for _ in range(3):
        store.counters.reclaim_idle(1)
        flush_columnstore_batch(store, False, PCTS, AGGS)
    p.parse_metric_fast(b"new.key:2|c", store.process)
    store.apply_all_pending()
    # the new key reuses the freed row
    batch2, _ = flush_columnstore_batch(store, False, PCTS, AGGS)
    names = [m.name for m in batch2.materialize()]
    assert names == ["new.key"]


def test_empty_store_flushes_empty_batch():
    store = _mk_store()
    batch, fwd = flush_columnstore_batch(store, True, PCTS, AGGS)
    assert len(batch) == 0
    assert batch.materialize() == []
    assert len(fwd) == 0


def test_status_checks_flow_through_extras():
    store = _mk_store()
    _feed(store, [b"svc.ok:1|sc"])
    batch, _ = flush_columnstore_batch(store, False, PCTS, AGGS)
    mats = batch.materialize()
    assert len(mats) == 1 and len(batch) == 1
    assert mats[0].name == "svc.ok"


def test_batch_flush_concurrent_with_intern_churn():
    """Flush assembly runs lock-free against ingest by design; interning
    (including recycled-row cache invalidation, which iterates the
    flush-name cache dict) must not race the flusher's cache-dict
    mutations (code-review finding: RuntimeError 'dictionary changed
    size during iteration' in row_for)."""
    import threading

    store = ColumnStore(counter_capacity=256, gauge_capacity=256,
                        histo_capacity=256, set_capacity=64, batch_cap=128)
    stop = threading.Event()
    errors = []

    def churn():
        p = Parser()
        i = 0
        try:
            while not stop.is_set():
                p.parse_metric_fast(
                    b"churn.%d:1|c|#k:v" % (i % 700), store.process)
                p.parse_metric_fast(
                    b"churn.t.%d:%d|ms" % (i % 300, i % 50), store.process)
                i += 1
        except Exception as e:  # pragma: no cover - the regression signal
            errors.append(e)

    threads = [threading.Thread(target=churn, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(25):
            flush_columnstore_batch(store, False, PCTS, AGGS)
            for table in (store.counters, store.histos):
                table.reclaim_idle(1)
    finally:
        stop.set()
        for t in threads:
            t.join(5)
    assert not errors, errors


def test_unfiltered_config_sink_takes_columnar_path():
    """A config-declared sink WITHOUT active filters must receive the
    columnar FlushBatch (fast path); one WITH filters gets the filtered
    InterMetric list. (Regression: _sink_filters used to hold an entry
    for every declared sink, so yaml-declared sinks always paid
    materialization.)"""
    from veneur_tpu.config import Config, SinkConfig
    from veneur_tpu.core.server import Server

    cfg = Config()
    cfg.interval = 60.0
    cfg.statsd_listen_addresses = []
    cfg.metric_sinks = [
        SinkConfig(kind="blackhole", name="plain"),
        SinkConfig(kind="blackhole", name="filtered",
                   strip_tags=[{"kind": "prefix", "value": "secret"}]),
        SinkConfig(kind="blackhole", name="maxtags", max_tags=1),
    ]
    cfg.apply_defaults()
    server = Server(cfg)
    calls = {}
    for sink in server.metric_sinks:
        name = sink.name()
        sink.flush_batch = (
            lambda b, n=name: calls.setdefault(n, ("batch", b)))
        sink.flush = (
            lambda ms, n=name: calls.setdefault(n, ("list", ms)))
    server.handle_metric_packet(b"fb.route:1|c|#secret:x,keep:y")
    server.store.apply_all_pending()
    server.flush()
    kind_plain, payload_plain = calls["plain"]
    kind_filtered, payload_filtered = calls["filtered"]
    assert kind_plain == "batch" and isinstance(payload_plain, FlushBatch)
    assert kind_filtered == "list"
    [m] = payload_filtered
    assert m.name == "fb.route" and m.tags == ["keep:y"]
    # max_tags alone is an active filter too (2-tag metric exceeds 1)
    kind_maxtags, payload_maxtags = calls["maxtags"]
    assert kind_maxtags == "list" and payload_maxtags == []
    server.shutdown()


def test_batch_flush_sharded_store_matches_single_device():
    """The columnar flush over an 8-way sharded store (virtual CPU mesh)
    must emit the same metrics as over a single-device store. batch_cap
    is tiny so the round-robin actually spreads interval state across
    shards; histogram-derived values compare with the same slack the
    sharded-equivalence suite uses (recompress over a merged grid may
    interpolate slightly differently)."""
    from veneur_tpu.core.sharded_tables import ShardedHistoTable

    lines = _mixed_corpus() * 3  # several batches per family
    s1 = ColumnStore(counter_capacity=64, gauge_capacity=64,
                     histo_capacity=64, set_capacity=32, batch_cap=16)
    s8 = ColumnStore(counter_capacity=64, gauge_capacity=64,
                     histo_capacity=64, set_capacity=32, batch_cap=16,
                     shard_devices=8)
    assert isinstance(s8.histos, ShardedHistoTable)  # no silent fallback
    assert len(s8.histos._devices) == 8
    _feed(s1, lines)
    _feed(s8, lines)
    b1, _ = flush_columnstore_batch(s1, False, PCTS, AGGS)
    b8, _ = flush_columnstore_batch(s8, False, PCTS, AGGS)

    def grouped(batch):
        out = {}
        for m in batch.materialize():
            out.setdefault(
                (m.name, int(m.type), tuple(sorted(m.tags))),
                []).append(float(m.value))
        return {k: sorted(v) for k, v in out.items()}

    g1, g8 = grouped(b1), grouped(b8)
    assert g1.keys() == g8.keys()
    for k in g1:
        np.testing.assert_allclose(g1[k], g8[k], rtol=0.05, atol=1e-6,
                                   err_msg=str(k))


def test_materialize_is_cached_and_shared():
    store = _mk_store()
    _feed(store, [b"a:1|c", b"b:2.5|g"])
    batch, _ = flush_columnstore_batch(store, False, PCTS, AGGS)
    assert batch.materialize() is batch.materialize()
