"""Overlapped flush & shape ladder (the `flushperf` marker).

The double-buffered flush (`flush_async`) swaps each family's device
generation at the interval boundary and runs the readout on a
background executor, delivering the PREVIOUS interval's joined readout
each tick. These tests pin the contract that makes that safe to ship:

- exactness: the overlapped flush's output is bit-identical to the
  synchronous flush for all five families (values, tags, llhist bins,
  HLL registers), single-device AND on the virtual mesh;
- the recycled (donated, re-initialized) spare generation is
  indistinguishable from a fresh allocation — interval N+1 over the
  recycled buffers equals interval N over fresh ones, including the
  t-digest ±inf min/max re-init;
- the ledger stays strict-clean through the overlap, with the
  in-flight snapshot booked as the `flush_inflight_snapshot` stock;
- shutdown (the SIGUSR2 handoff's drain) joins and delivers the
  in-flight snapshot — nothing is lost at the seam, and in WAL mode
  the snapshot reaches disk before the process exits;
- the waterfall renders the overlapped shape (async lane, join-only
  `critical_path_s`) and async `flush.family` spans parent under the
  originating interval's flush trace;
- a prewarmed capacity rung's post-resize round tags
  `prewarmed`/`compile_cache` instead of paying a hot-path retrace,
  and the cold (un-prewarmed) fallback stays correct.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.columnstore import ColumnStore, CounterTable
from veneur_tpu.core.flusher import (flush_columnstore_batch,
                                     readout_columnstore,
                                     swap_columnstore)
from veneur_tpu.core.server import Server
from veneur_tpu.samplers.metrics import HistogramAggregates
from veneur_tpu.samplers.parser import Parser
from veneur_tpu.sinks.channel import ChannelMetricSink

pytestmark = pytest.mark.flushperf

PCTS = (0.5, 0.99)
AGGS = HistogramAggregates.from_names(
    ["min", "max", "median", "avg", "count", "sum"])


def wait_until(fn, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def corpus(round_no: int = 0):
    lines = []
    for i in range(8):
        lines.append(b"c.%d:%d|c|#env:t" % (i, i + 1 + round_no))
        lines.append(b"g.%d:%.2f|g" % (i, i * 1.5 + round_no))
        lines.append(b"t.%d:%.2f|ms" % (i, 10.0 + i + round_no))
        lines.append(b"t.%d:%.2f|ms" % (i, 40.0 + i))
        lines.append(b"s.%d:m%d|s" % (i, i))
        lines.append(b"s.%d:m%d|s" % (i, i + 50 + round_no))
        lines.append(b"ll.%d:%.2f|l" % (i, 3.0 + i + round_no))
    lines.append(b"sc.ok:0|sc")
    return lines


def _mk_store(**kw):
    kw.setdefault("counter_capacity", 64)
    kw.setdefault("gauge_capacity", 64)
    kw.setdefault("histo_capacity", 64)
    kw.setdefault("set_capacity", 32)
    kw.setdefault("llhist_capacity", 64)
    kw.setdefault("batch_cap", 128)
    return ColumnStore(**kw)


def _feed(store, lines):
    p = Parser()
    for line in lines:
        p.parse_metric_fast(line, store.process)
    store.apply_all_pending()


def _batch_keys(batch):
    return sorted(
        (m.name, float(m.value), tuple(sorted(m.tags)), int(m.type))
        for m in batch.materialize())


def _fwd_keys(fwd):
    """Bit-level ForwardableState fingerprint: scalar values exact,
    llhist bins and HLL registers compared register-for-register."""
    return {
        "counters": sorted((m.name, v) for m, v in fwd.counters),
        "gauges": sorted((m.name, v) for m, v in fwd.gauges),
        "histos": sorted(
            (m.name, means.tobytes(), weights.tobytes(), lo, hi, recip)
            for m, means, weights, lo, hi, recip in fwd.histograms),
        "sets": sorted((m.name, np.asarray(regs).tobytes())
                       for m, regs in fwd.sets),
        "llhists": sorted((m.name, np.asarray(bins).tobytes())
                          for m, bins in fwd.llhists),
    }


def _overlapped_flush(store, is_local, collect_forward=True):
    """Swap on this thread (the interval boundary), read out on a
    background thread while this thread keeps ingesting — the exact
    overlap shape the server runs under flush_async."""
    swap = swap_columnstore(store, is_local, PCTS,
                            collect_forward=collect_forward)
    result = {}

    def _readout():
        result["out"] = readout_columnstore(
            store, swap, is_local, AGGS,
            collect_forward=collect_forward)

    t = threading.Thread(target=_readout)
    t.start()
    # ingest the NEXT interval concurrently with the readout
    _feed(store, corpus(round_no=7))
    t.join(30.0)
    assert not t.is_alive()
    return result["out"]


class TestOverlapExactness:
    @pytest.mark.parametrize("is_local", [False, True])
    def test_async_bit_identical_single_device(self, is_local):
        """Overlapped flush == synchronous flush, all five families,
        for both server modes — AND the recycled spare generation's
        second interval equals a fresh store's."""
        sync_store, async_store = _mk_store(), _mk_store()
        _feed(sync_store, corpus())
        _feed(async_store, corpus())
        sync_batch, sync_fwd = flush_columnstore_batch(
            sync_store, is_local, PCTS, AGGS)
        async_batch, async_fwd = _overlapped_flush(async_store, is_local)
        assert _batch_keys(async_batch) == _batch_keys(sync_batch)
        assert _fwd_keys(async_fwd) == _fwd_keys(sync_fwd)
        # interval 2: the async store now flushes over RECYCLED
        # (donated, re-initialized) generations; feed the sync store
        # the same second-interval corpus and compare again
        _feed(sync_store, corpus(round_no=7))
        sync2, sfwd2 = flush_columnstore_batch(
            sync_store, is_local, PCTS, AGGS)
        async2, afwd2 = flush_columnstore_batch(
            async_store, is_local, PCTS, AGGS)
        assert _batch_keys(async2) == _batch_keys(sync2)
        assert _fwd_keys(afwd2) == _fwd_keys(sfwd2)

    @pytest.mark.mesh
    def test_async_bit_identical_on_mesh(self):
        """The overlapped flush over the sharded mesh store (stacked
        donated merges) matches the single-device synchronous flush
        bit-for-bit — the PR-11 exactness pin survives the overlap."""
        single = _mk_store()
        mesh_store = _mk_store(shard_devices=2)
        assert mesh_store.shard_plane is not None, "virtual mesh missing"
        _feed(single, corpus())
        _feed(mesh_store, corpus())
        sync_batch, sync_fwd = flush_columnstore_batch(
            single, True, PCTS, AGGS)
        async_batch, async_fwd = _overlapped_flush(mesh_store, True)
        assert _batch_keys(async_batch) == _batch_keys(sync_batch)
        assert _fwd_keys(async_fwd) == _fwd_keys(sync_fwd)
        # second interval over the recycled stacked generations
        _feed(single, corpus(round_no=7))
        sync2, sfwd2 = flush_columnstore_batch(single, True, PCTS, AGGS)
        async2, afwd2 = flush_columnstore_batch(mesh_store, True, PCTS,
                                                AGGS)
        assert _batch_keys(async2) == _batch_keys(sync2)
        assert _fwd_keys(afwd2) == _fwd_keys(sfwd2)


# -------------------------------------------------------------------------
# Server pipeline: delivery cadence, ledger, waterfall, drain
# -------------------------------------------------------------------------


def mk_server(**kw):
    cfg = Config()
    cfg.interval = 60.0
    cfg.hostname = "test"
    cfg.statsd_listen_addresses = []
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    cfg.ledger_strict = True
    for k, v in kw.items():
        if "." in k:
            ns, field = k.split(".", 1)
            setattr(getattr(cfg, ns), field, v)
        else:
            setattr(cfg, k, v)
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[obs]), obs


def _server_feed(server, lines):
    for line in lines:
        server.handle_metric_packet(line)
    server.store.apply_all_pending()


def _obs_keys(metrics):
    return sorted((m.name, float(m.value), tuple(sorted(m.tags)),
                   int(m.type)) for m in metrics)


class TestServerPipeline:
    def test_async_delivers_previous_interval_strict_ledger(self):
        """Tick N delivers interval N-1's readout bit-identically to a
        synchronous server, the first tick delivers nothing, and
        ledger_strict stays green through the overlap (the in-flight
        snapshot is stock, not loss)."""
        sync_server, sync_obs = mk_server(flush_async=False)
        async_server, async_obs = mk_server(flush_async=True)
        try:
            _server_feed(sync_server, corpus())
            sync_server.flush()
            sync_metrics = sync_obs.drain()
            assert sync_metrics

            _server_feed(async_server, corpus())
            async_server.flush()  # tick 1: swap + submit, no delivery
            assert async_obs.drain() == []
            # while interval 1's readout drains in the background, the
            # inflight stock is visible to the ledger
            assert async_server._inflight_flushes
            assert async_server._inflight_rows > 0
            wait_until(lambda: async_server._inflight_flushes[0]["pending"].done())
            async_server.flush()  # tick 2: joins + delivers interval 1
            got = async_obs.drain()
            assert _obs_keys(got) == _obs_keys(sync_metrics)
            ri = async_server.telemetry.flushes.snapshot()[-1]
            assert ri["async"] is True
            assert ri["delivered_flush"] == 1
            # critical path excludes the dispatch/sync/transfer phases
            # by construction: they ran on the executor thread
            assert "critical_path_s" in ri["phases"]
            assert ri["ledger"] == {} or all(
                abs(v) < 1e-6 for v in ri["ledger"].values())
        finally:
            sync_server.config.flush_on_shutdown = False
            async_server.config.flush_on_shutdown = False
            sync_server.shutdown()
            async_server.shutdown()

    def test_waterfall_renders_async_lane(self):
        server, obs = mk_server(flush_async=True)
        try:
            from veneur_tpu.core.latency import waterfall_rounds
            _server_feed(server, corpus())
            server.flush()
            wait_until(lambda: server._inflight_flushes[0]["pending"].done())
            server.flush()
            rounds = waterfall_rounds(server.telemetry.flushes.snapshot())
            tree = rounds[-1]
            assert tree["async_readout"] is True
            assert tree["delivered_flush"] == 1
            assert tree["critical_path_s"] >= 0.0
            assert tree["families"]
            for rec in tree["families"].values():
                assert rec["lane"] == "async"
            # the segments-sum pin holds for the overlapped shape too:
            # segments AND phase totals come from the same readout
            assert tree["segments_sum_s"] <= tree["device_total_s"] * 1.10
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_async_family_spans_parent_under_origin_interval(self):
        """PR-9 single-root pin under overlap: the async readout's
        flush.family spans land in the ORIGINATING interval's trace,
        parented under its flush span — not the delivering tick's."""
        server, obs = mk_server(flush_async=True)
        try:
            _server_feed(server, corpus())
            server.flush()
            tid1 = server.telemetry.flushes.snapshot()[-1].get("trace_id")
            assert tid1
            wait_until(lambda: server._inflight_flushes[0]["pending"].done())
            server.flush()
            trace = server.trace_plane.store.get(int(tid1, 16))
            spans = trace["spans"]
            assert len(trace["roots"]) == 1  # PR-9 single-root pin
            root = next(s for s in spans
                        if s["span_id"] == trace["roots"][0])
            assert root["name"] == "flush"
            fam_spans = [s for s in spans if s["name"] == "flush.family"]
            assert fam_spans, "async flush.family spans missing"
            for s in fam_spans:
                assert s["parent_id"] == root["span_id"]
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_shutdown_drains_inflight_and_final_interval(self):
        """The drain seam (shutdown / SIGUSR2 handoff): an in-flight
        async readout AND the just-swapped final interval both deliver
        before the process exits."""
        server, obs = mk_server(flush_async=True,
                                flush_on_shutdown=True)
        sync_server, sync_obs = mk_server(flush_async=False)
        try:
            _server_feed(sync_server, corpus())
            sync_server.flush()
            want = _obs_keys(sync_obs.drain())

            _server_feed(server, corpus())
            server.flush()  # interval 1 swapped, readout in flight
            assert server._inflight_flushes
            _server_feed(server, corpus(round_no=3))
        finally:
            sync_server.config.flush_on_shutdown = False
            sync_server.shutdown()
            server.shutdown()
        got = obs.drain()
        assert _obs_keys([m for m in got])  # both intervals landed
        # interval 1's metrics are exactly the sync server's
        names = {m.name for m in got}
        assert {n for n, *_ in want} <= names
        # and the final interval's distinct corpus landed too
        assert len(got) > len(want) / 2

    def test_shutdown_drain_reaches_wal(self, tmp_path):
        """WAL mode + dead upstream: the handoff drain appends the
        in-flight snapshot to the on-disk WAL before exiting — a crash
        after shutdown loses nothing (PR-10's replay picks it up)."""
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.util.resilience import CircuitBreaker, RetryPolicy
        from veneur_tpu.util.spool import CarryoverSpool

        server, obs = mk_server(flush_async=True, forward_only=True,
                                forward_address="127.0.0.1:1")
        spool = CarryoverSpool(str(tmp_path))
        client = ForwardClient(  # dead upstream: WAL append still lands
            "127.0.0.1:1", deadline=3.0, spool=spool, wal=True,
            retry=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker(failure_threshold=10_000, name="t"))
        server.forwarder = client.forward
        server.forward_client = client
        # the stocks start() would have registered: the strict forward
        # identity must see WAL-spooled metrics as inventory
        server.ledger.stock("forward_carryover",
                            lambda: client.carryover.pending_metrics)
        server.ledger.stock("forward_inflight",
                            lambda: client.inflight_metrics)
        server.ledger.stock("forward_spool",
                            lambda: spool.pending_metrics)
        server.ledger.stock("spool_quarantine",
                            lambda: spool.quarantined_metrics)
        try:
            _server_feed(server, corpus())
            server.flush()  # swap + submit; nothing on disk yet
            assert server._inflight_flushes
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()  # drain: join + deliver -> WAL append
            client.close()
        assert client.wal_appended_metrics > 0
        assert spool.depth >= 1  # durable, awaiting replay


# -------------------------------------------------------------------------
# Shape-ladder prewarm
# -------------------------------------------------------------------------


class TestShapeLadder:
    def _force_resize(self, table, parser, n=80):
        for i in range(n):
            parser.parse_metric_fast(b"pw.%d:1|c" % i, table.add)
        table.apply_pending()

    def test_prewarmed_resize_tags_and_stays_correct(self):
        """A prewarmed rung's post-resize apply reports prewarmed=True
        through the resize hook (the waterfall tag), and the values
        coming out of the resized table are exact."""
        store = _mk_store(counter_capacity=64)
        table = store.counters
        events = []
        table.on_resize = lambda *a, **kw: events.append((a, kw))
        assert table.prewarm_rung(128, PCTS)
        assert 128 in table._prewarmed_caps
        self._force_resize(table, Parser())
        recompiles = [kw for a, kw in events
                      if kw.get("kind") == "recompile"]
        assert recompiles and recompiles[0]["prewarmed"] is True
        vals, touched, meta = table.snapshot_and_reset()
        got = {meta[r].name: vals[r] for r in np.flatnonzero(touched)}
        assert got == {f"pw.{i}": 1.0 for i in range(80)}

    def test_cold_resize_fallback_still_correct(self):
        """Without prewarm the resize retraces on the hot path (the
        pre-ladder behavior): tagged prewarmed=False, values exact."""
        store = _mk_store(counter_capacity=64)
        table = store.counters
        events = []
        table.on_resize = lambda *a, **kw: events.append((a, kw))
        self._force_resize(table, Parser())
        recompiles = [kw for a, kw in events
                      if kw.get("kind") == "recompile"]
        assert recompiles and recompiles[0]["prewarmed"] is False
        vals, touched, meta = table.snapshot_and_reset()
        assert len(np.flatnonzero(touched)) == 80

    def test_prewarmer_thread_compiles_queued_rungs(self):
        """ShapeLadderPrewarmer end to end: initial prewarm queues 2x
        rungs for every device family; a resize event queues the rung
        after; every compile lands in the table's prewarmed set."""
        from veneur_tpu.core.flushexec import ShapeLadderPrewarmer

        store = _mk_store()
        events = []
        pw = ShapeLadderPrewarmer(
            store, percentiles=PCTS, need_export=True,
            on_event=lambda kind, **kw: events.append((kind, kw)))
        pw.start()
        try:
            pw.prewarm_initial()
            assert wait_until(
                lambda: 128 in store.counters._prewarmed_caps
                and 128 in store.gauges._prewarmed_caps
                and 128 in store.histos._prewarmed_caps
                and 128 in store.llhists._prewarmed_caps, timeout=60.0)
            # the sparse set table's rung prewarm is a documented no-op
            assert not store.sets._prewarmed_caps
            pw.note_resize("counter", 128)
            assert wait_until(
                lambda: 256 in store.counters._prewarmed_caps,
                timeout=60.0)
            assert pw.compiled_total >= 5
            rows = {name: v for name, _k, v, _t in pw.telemetry_rows()}
            assert rows["prewarm.compiled_total"] >= 5
        finally:
            pw.stop()

    def test_server_recompile_event_reads_prewarmed(self):
        """Server-side tag plumbing: a prewarmed recompile lands in the
        flight recorder + retrace cache as prewarmed (the waterfall's
        `compile_cache: prewarmed` tag the acceptance reads)."""
        server, obs = mk_server()
        try:
            server._store_resize("counter", 64, 128, 0.01, kind="resize")
            server._store_resize("counter", 64, 128, 0.002,
                                 kind="recompile", prewarmed=True)
            events = [e for e in server.telemetry.events.snapshot()
                      if e["kind"] == "columnstore_recompile"]
            assert events and events[-1]["prewarmed"] is True
            assert events[-1].get("compile_cache") in ("prewarmed", "hit")
            drained = server.latency.drain_retraces()
            secs, cache = drained["counter"]
            assert cache in ("prewarmed", "hit")
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()


class TestReadoutExecutor:
    def test_join_reraises_and_survives(self):
        from veneur_tpu.core.flushexec import FlushReadoutExecutor

        beats = []
        ex = FlushReadoutExecutor(beat=beats.append)
        try:
            boom = ex.submit(lambda: 1 / 0)
            with pytest.raises(ZeroDivisionError):
                boom.result(5.0)
            ok = ex.submit(lambda: 42)
            assert ok.result(5.0) == 42
            assert beats  # supervisor heartbeats flowed
        finally:
            ex.stop()
