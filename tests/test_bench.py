"""The benchmark artifact contract: `python bench.py` must ALWAYS print
exactly one JSON line with the driver-required keys and exit 0 — on
success, on deadline expiry (partial result), and on CPU fallback.
Rounds 1 and 2 lost their perf artifacts to driver-side timeouts; these
tests pin the resilience behaviors that fixed that."""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench(*args, timeout=180):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # conftest pins an 8-virtual-device mesh for the in-process suite;
    # the bench subprocess must see the topology the driver's standalone
    # `python bench.py` run sees
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", "")).strip()
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    return proc


def last_json_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, f"expected exactly one JSON line, got: {lines}"
    return json.loads(lines[0])


@pytest.fixture(scope="module")
def single_proc():
    return run_bench("--scenario", "single", "--duration", "1",
                     "--keys", "500", "--deadline", "150")


class TestBenchContract:
    def test_single_scenario_emits_contract_keys(self, single_proc):
        proc = single_proc
        assert proc.returncode == 0, proc.stderr[-2000:]
        obj = last_json_line(proc.stdout)
        for key in ("metric", "value", "unit", "vs_baseline"):
            assert key in obj, key
        assert obj["metric"] == "dogstatsd_samples_per_sec"
        assert obj["value"] > 0
        assert obj["unit"] == "samples/s"

    def test_deadline_emits_partial_json_rc0(self):
        """A too-tight budget must still land a parseable line with
        truncated=true and exit 0 — never a silent driver timeout."""
        proc = run_bench("--scenario", "single", "--duration", "60",
                         "--keys", "2000", "--deadline", "12", timeout=90)
        assert proc.returncode == 0, proc.stderr[-2000:]
        obj = last_json_line(proc.stdout)
        assert obj.get("truncated") is True
        assert "metric" in obj and "vs_baseline" in obj

    def test_progress_lines_on_stderr(self, single_proc):
        """Timestamped stage lines make a driver-side timeout tail
        diagnosable."""
        proc = single_proc
        assert "bench[" in proc.stderr
        assert "backend=" in proc.stderr

    def test_llhist_scenario_smoke(self):
        """The llhist BASELINE config must run and emit its contract
        line (the log-linear family rides the Python parse path, so
        this also smoke-tests `|l` ingest end to end)."""
        proc = run_bench("--scenario", "llhist", "--duration", "1",
                         "--keys", "200", "--deadline", "150")
        assert proc.returncode == 0, proc.stderr[-2000:]
        obj = last_json_line(proc.stdout)
        assert obj["metric"] == "llhist_samples_per_sec"
        assert obj["value"] > 0
        assert obj["unit"] == "samples/s"
