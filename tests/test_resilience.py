"""Egress resilience layer tests: retry backoff budgets, circuit breaker
state transitions, lossless carryover merges, sink thread caps and
spill (util/resilience.py + the core/server.py and proxy wiring)."""

import threading
import time

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.flusher import ForwardableState
from veneur_tpu.core.server import Server
from veneur_tpu.ops.batch_tdigest import C, COMPRESSION
from veneur_tpu.ops.tdigest_ref import MergingDigest
from veneur_tpu.sinks.channel import ChannelMetricSink
from veneur_tpu.util.resilience import (
    CLOSED, HALF_OPEN, OPEN, Carryover, CircuitBreaker, RetryPolicy,
    merge_centroids, merge_forwardable)


def make_config(**overrides) -> Config:
    cfg = Config()
    cfg.interval = 10.0
    cfg.hostname = "test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


def _mk_meta(name, wire_type="counter", tags=()):
    from veneur_tpu.core.columnstore import RowMeta
    from veneur_tpu.samplers.metrics import MetricScope
    return RowMeta(name=name, tags=list(tags), joined_tags=",".join(tags),
                   digest32=1, scope=MetricScope.GLOBAL_ONLY,
                   wire_type=wire_type)


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def sleep(self, s):
        self.now += s


# -------------------------------------------------------------------------
# RetryPolicy
# -------------------------------------------------------------------------


class TestRetryPolicy:
    def test_delay_count_bounded_by_attempts(self):
        clock = FakeClock()
        policy = RetryPolicy(max_attempts=4, base_delay=0.1, max_delay=1.0,
                             clock=clock)
        assert len(list(policy.delays(budget=1e9))) == 3

    def test_delays_respect_budget(self):
        clock = FakeClock()
        policy = RetryPolicy(max_attempts=50, base_delay=1.0, max_delay=1.0,
                             clock=clock)
        spent = 0.0
        for delay in policy.delays(budget=3.0):
            clock.sleep(delay)
            spent += delay
        assert spent <= 3.0

    def test_delays_grow_up_to_cap(self):
        class TopRng:  # always the top of the uniform range
            def uniform(self, a, b):
                return b

        clock = FakeClock()
        policy = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.5,
                             multiplier=2.0, rng=TopRng(), clock=clock)
        assert list(policy.delays(budget=1e9)) == \
            pytest.approx([0.1, 0.2, 0.4, 0.5])


# -------------------------------------------------------------------------
# CircuitBreaker
# -------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_full_transition_cycle(self):
        """closed -> open -> half-open -> closed, the satellite's pinned
        sequence."""
        clock = FakeClock()
        transitions = []
        breaker = CircuitBreaker(
            failure_threshold=3, recovery_time=30.0, clock=clock,
            name="t", on_transition=lambda n, o, new: transitions.append(
                (o, new)))
        assert breaker.state == CLOSED
        for _ in range(2):
            breaker.record_failure()
        assert breaker.state == CLOSED          # under threshold
        breaker.record_failure()
        assert breaker.state == OPEN
        assert not breaker.allow()
        clock.sleep(29.0)
        assert breaker.state == OPEN            # still cooling down
        clock.sleep(1.5)
        assert breaker.state == HALF_OPEN
        assert breaker.allow()                  # the single probe
        breaker.record_success()
        assert breaker.state == CLOSED
        assert transitions == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                               (HALF_OPEN, CLOSED)]

    def test_half_open_single_probe(self):
        clock = FakeClock()
        breaker = CircuitBreaker(failure_threshold=1, recovery_time=1.0,
                                 clock=clock)
        breaker.record_failure()
        clock.sleep(2.0)
        assert breaker.allow() is True      # first caller wins the probe
        assert breaker.allow() is False     # everyone else refused
        assert breaker.refused_total >= 1

    def test_half_open_probe_failure_reopens(self):
        clock = FakeClock()
        breaker = CircuitBreaker(failure_threshold=1, recovery_time=1.0,
                                 clock=clock)
        breaker.record_failure()
        clock.sleep(2.0)
        assert breaker.allow()
        breaker.record_failure()
        assert breaker.state == OPEN
        assert breaker.open_total == 2

    def test_success_resets_failure_streak(self):
        breaker = CircuitBreaker(failure_threshold=3)
        for _ in range(10):
            breaker.record_failure()
            breaker.record_failure()
            breaker.record_success()
        assert breaker.state == CLOSED

    def test_is_dispatchable_does_not_consume_probe(self):
        clock = FakeClock()
        breaker = CircuitBreaker(failure_threshold=1, recovery_time=1.0,
                                 clock=clock)
        breaker.record_failure()
        assert breaker.is_dispatchable is False    # open
        clock.sleep(2.0)
        assert breaker.is_dispatchable is True     # half-open
        assert breaker.allow() is True             # probe still available

    def test_state_codes(self):
        clock = FakeClock()
        breaker = CircuitBreaker(failure_threshold=1, recovery_time=1.0,
                                 clock=clock)
        assert breaker.state_code == 0
        breaker.record_failure()
        assert breaker.state_code == 1
        clock.sleep(2.0)
        assert breaker.state_code == 2

    def test_thread_safety_smoke(self):
        breaker = CircuitBreaker(failure_threshold=5, recovery_time=0.0)
        errs = []

        def pound():
            try:
                for _ in range(500):
                    if breaker.allow():
                        breaker.record_success()
                    breaker.record_failure()
                    _ = breaker.state_code
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=pound) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs


# -------------------------------------------------------------------------
# Carryover merge semantics
# -------------------------------------------------------------------------


def _digest_row(values, weight=1.0):
    """Build a (means, weights) C-slot f32 row from raw samples."""
    means = np.zeros(C, np.float32)
    weights = np.zeros(C, np.float32)
    means[:len(values)] = values
    weights[:len(values)] = weight
    return means, weights


class TestMergeSemantics:
    def test_counters_sum(self):
        newer = ForwardableState(counters=[(_mk_meta("a"), 3.0),
                                           (_mk_meta("b"), 1.0)])
        older = ForwardableState(counters=[(_mk_meta("a"), 4.0),
                                           (_mk_meta("c"), 7.0)])
        merge_forwardable(newer, older)
        got = {m.name: v for m, v in newer.counters}
        assert got == {"a": 7.0, "b": 1.0, "c": 7.0}

    def test_gauges_last_write_wins(self):
        newer = ForwardableState(gauges=[(_mk_meta("g", "gauge"), 5.0)])
        older = ForwardableState(gauges=[(_mk_meta("g", "gauge"), 99.0),
                                         (_mk_meta("old", "gauge"), 2.0)])
        merge_forwardable(newer, older)
        got = {m.name: v for m, v in newer.gauges}
        # the newer interval's value wins; an old-only gauge is carried
        assert got == {"g": 5.0, "old": 2.0}

    def test_sets_register_max(self):
        a = np.zeros(16, np.uint8)
        b = np.zeros(16, np.uint8)
        a[2], b[2], b[7] = 5, 3, 9
        newer = ForwardableState(sets=[(_mk_meta("s", "set"), a)])
        older = ForwardableState(sets=[(_mk_meta("s", "set"), b)])
        merge_forwardable(newer, older)
        merged = newer.sets[0][1]
        assert merged[2] == 5 and merged[7] == 9

    def test_tags_distinguish_rows(self):
        newer = ForwardableState(
            counters=[(_mk_meta("a", tags=("env:prod",)), 1.0)])
        older = ForwardableState(
            counters=[(_mk_meta("a", tags=("env:dev",)), 10.0)])
        merge_forwardable(newer, older)
        assert len(newer.counters) == 2

    def test_digest_merge_conserves_weight_min_max_recip(self):
        m1, w1 = _digest_row([1.0, 2.0, 3.0])
        m2, w2 = _digest_row([10.0, 20.0])
        newer = ForwardableState(
            histograms=[(_mk_meta("h", "histogram"), m1, w1, 1.0, 3.0, 0.5)])
        older = ForwardableState(
            histograms=[(_mk_meta("h", "histogram"), m2, w2, 10.0, 20.0,
                         0.15)])
        merge_forwardable(newer, older)
        meta, mm, ww, dmin, dmax, drecip = newer.histograms[0]
        assert ww.sum() == pytest.approx(5.0)
        assert (dmin, dmax) == (1.0, 20.0)
        assert drecip == pytest.approx(0.65)
        assert mm.shape == (C,) and mm.dtype == np.float32

    def test_merge_centroids_matches_reference_quantiles(self):
        """Concatenate-and-recompress must stay in the same accuracy
        class as the scalar reference digest over the union stream."""
        rng = np.random.default_rng(11)
        s1 = rng.normal(100.0, 15.0, 400)
        s2 = rng.normal(140.0, 5.0, 300)
        d1, d2 = MergingDigest(COMPRESSION), MergingDigest(COMPRESSION)
        for v in s1:
            d1.add(float(v))
        for v in s2:
            d2.add(float(v))
        d1._merge_all_temps()
        d2._merge_all_temps()
        mm, ww = merge_centroids(
            np.array(d1.means), np.array(d1.weights),
            np.array(d2.means), np.array(d2.weights), C, COMPRESSION)
        assert ww.sum() == pytest.approx(700.0)
        merged = MergingDigest.from_centroids(
            mm[ww > 0].tolist(), ww[ww > 0].tolist(),
            float(min(s1.min(), s2.min())), float(max(s1.max(), s2.max())),
            compression=COMPRESSION)
        both = np.sort(np.concatenate([s1, s2]))
        for q in (0.25, 0.5, 0.9, 0.99):
            want = both[int(q * len(both))]
            assert merged.quantile(q) == pytest.approx(want, rel=0.05), q

    def test_merge_centroids_empty_sides(self):
        m, w = _digest_row([5.0])
        zm, zw = np.zeros(C, np.float32), np.zeros(C, np.float32)
        mm, ww = merge_centroids(m, w, zm, zw, C, COMPRESSION)
        assert ww.sum() == pytest.approx(1.0)
        mm, ww = merge_centroids(zm, zw, zm, zw, C, COMPRESSION)
        assert ww.sum() == 0.0


class TestCarryover:
    def test_stash_drain_roundtrip(self):
        co = Carryover(max_intervals=3)
        failed = ForwardableState(counters=[(_mk_meta("a"), 2.0)])
        co.stash(failed)
        assert co.depth == 1
        nxt = ForwardableState(counters=[(_mk_meta("a"), 3.0)])
        merged = co.drain_into(nxt)
        assert merged.counters[0][1] == 5.0
        assert co.drain_into(ForwardableState()).counters == []  # cleared
        co.clear_age()
        assert co.depth == 0

    def test_shed_beyond_bound(self):
        co = Carryover(max_intervals=2)
        for i in range(2):
            co.stash(co.drain_into(
                ForwardableState(counters=[(_mk_meta("a"), 1.0)])))
        assert co.depth == 2 and co.shed_total == 0
        co.stash(co.drain_into(
            ForwardableState(counters=[(_mk_meta("a"), 1.0)])))
        # third consecutive failure exceeds the bound: everything sheds
        assert co.shed_total > 0
        assert co.depth == 0
        assert len(co.drain_into(ForwardableState())) == 0

    def test_zero_intervals_disables(self):
        co = Carryover(max_intervals=0)
        co.stash(ForwardableState(counters=[(_mk_meta("a"), 1.0)]))
        assert co.depth == 0 and co.shed_total == 1
        assert len(co.drain_into(ForwardableState())) == 0

    def test_fail_then_succeed_equals_never_failing(self):
        """The satellite's equivalence pin: two intervals delivered as
        one carryover-merged send carry exactly the same counters and
        the same recompressed digest as merging the intervals directly."""
        def interval(seed, count_val):
            rng = np.random.default_rng(seed)
            means = np.zeros(C, np.float32)
            weights = np.zeros(C, np.float32)
            n = 40
            means[:n] = rng.normal(50, 10, n).astype(np.float32)
            weights[:n] = 1.0
            return ForwardableState(
                counters=[(_mk_meta("cnt"), count_val)],
                histograms=[(_mk_meta("h", "histogram"), means, weights,
                             float(means[:n].min()), float(means[:n].max()),
                             0.0)])

        # path A: interval 1 fails, is stashed, merges into interval 2
        co = Carryover(max_intervals=5)
        co.stash(interval(1, 10.0))
        delivered = co.drain_into(interval(2, 7.0))
        # path B: the same two intervals merged directly (never "failed")
        control = merge_forwardable(interval(2, 7.0), interval(1, 10.0))

        assert delivered.counters[0][1] == control.counters[0][1] == 17.0
        _, am, aw, amin, amax, _ = delivered.histograms[0]
        _, bm, bw, bmin, bmax, _ = control.histograms[0]
        np.testing.assert_array_equal(am, bm)
        np.testing.assert_array_equal(aw, bw)
        assert (amin, amax) == (bmin, bmax)


# -------------------------------------------------------------------------
# Server sink wiring: thread cap, pileup accounting, breaker, spill
# -------------------------------------------------------------------------


class HangingSink:
    """A metric sink whose flush never returns (until released)."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def name(self):
        return "hang"

    def start(self, server):
        pass

    def stop(self):
        pass

    def flush(self, metrics):
        self.calls += 1
        self.release.wait(timeout=60.0)

    def flush_other_samples(self, samples):
        pass


class FailingSink:
    """Fails `fail_times` flushes, then records what it receives."""

    def __init__(self, fail_times):
        self.fail_times = fail_times
        self.calls = 0
        self.received = []

    def name(self):
        return "flaky"

    def start(self, server):
        pass

    def stop(self):
        pass

    def flush(self, metrics):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise RuntimeError("sink down")
        self.received.extend(metrics)

    def flush_other_samples(self, samples):
        pass


def _live_flush_threads(key):
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name == f"flush-{key}"]


class TestServerSinkResilience:
    def test_hung_sink_capped_at_one_thread_and_breaker_opens(self):
        """The acceptance pin: a permanently-down sink ends at exactly
        one live flush thread plus an OPEN breaker gauge in /metrics —
        no per-interval thread growth."""
        sink = HangingSink()
        cfg = make_config(interval=0.4,
                          circuit_breaker_failure_threshold=3)
        server = Server(cfg, extra_metric_sinks=[sink])
        try:
            for i in range(5):
                server.handle_metric_packet(b"hang.c:1|c")
                server.flush()
            assert len(_live_flush_threads("metric:hang")) == 1
            breaker = server._sink_breakers["metric:hang"]
            assert breaker.state == OPEN
            assert server._sink_skip_depth["metric:hang"] >= 3
            exposition = server.telemetry.registry.render_prometheus()
            assert ('veneur_resilience_breaker_state{target="metric:hang"}'
                    ' 1') in exposition
            assert "veneur_flush_sink_pileup_depth" in exposition
        finally:
            sink.release.set()
            server.shutdown()

    def test_failed_batch_spills_one_interval_then_delivers(self):
        sink = FailingSink(fail_times=1)
        cfg = make_config(interval=2.0)
        server = Server(cfg, extra_metric_sinks=[sink])
        try:
            server.handle_metric_packet(b"spill.a:1|c")
            server.flush()          # fails; the batch spills
            assert "metric:flaky" in server._sink_spill
            server.handle_metric_packet(b"spill.b:1|c")
            server.flush()          # spill + new batch both delivered
            names = {m.name for m in sink.received}
            assert {"spill.a", "spill.b"} <= names
            assert "metric:flaky" not in server._sink_spill
        finally:
            server.shutdown()

    def test_spill_is_bounded_to_one_interval(self):
        sink = FailingSink(fail_times=2)
        cfg = make_config(interval=2.0)
        server = Server(cfg, extra_metric_sinks=[sink])
        try:
            server.handle_metric_packet(b"shed.a:1|c")
            server.flush()          # fail 1: a spills
            server.handle_metric_packet(b"shed.b:1|c")
            server.flush()          # fail 2: a sheds, b spills
            spilled = {m.name for m in
                       server._sink_spill.get("metric:flaky", [])}
            assert spilled == {"shed.b"}
            snap = server.telemetry.registry.snapshot()
            shed = [v for k, v in snap["counters"].items()
                    if k.startswith("flush.spill_shed_total")]
            assert shed and shed[0] >= 1.0
            server.handle_metric_packet(b"shed.c:1|c")
            server.flush()          # success: b (spill) + c delivered
            names = {m.name for m in sink.received}
            assert {"shed.b", "shed.c"} <= names
            assert "shed.a" not in names  # the shed interval is gone
        finally:
            server.shutdown()

    def test_sink_breaker_open_skips_dispatch(self):
        sink = FailingSink(fail_times=3)
        cfg = make_config(interval=2.0,
                          circuit_breaker_failure_threshold=3,
                          circuit_breaker_recovery=3600.0)
        server = Server(cfg, extra_metric_sinks=[sink])
        try:
            for i in range(3):
                server.handle_metric_packet(b"brk.x:1|c")
                server.flush()
            assert server._sink_breakers["metric:flaky"].state == OPEN
            calls_before = sink.calls
            server.handle_metric_packet(b"brk.y:1|c")
            server.flush()
            assert sink.calls == calls_before  # dispatch skipped
            snap = server.telemetry.registry.snapshot()
            opens = [v for k, v in snap["counters"].items()
                     if k.startswith("flush.sink_breaker_open_total")]
            assert opens and opens[0] >= 1.0
        finally:
            server.shutdown()


# -------------------------------------------------------------------------
# Proxy destination breaker
# -------------------------------------------------------------------------


class TestDestinationBreaker:
    def test_open_breaker_sheds_without_blocking(self):
        from veneur_tpu.forward.protos import metric_pb2
        from veneur_tpu.proxy.destinations import Destination

        dest = Destination("127.0.0.1:1", on_close=lambda d: None,
                           send_buffer=4, flush_interval=5.0,
                           max_consecutive_failures=1)
        try:
            dest.breaker.record_failure()  # opens (threshold 1)
            pbm = metric_pb2.Metric(name="x", type=metric_pb2.Counter)
            start = time.monotonic()
            assert dest.send(pbm) is False
            # pre-breaker behavior stalled up to flush_interval (5 s)
            assert time.monotonic() - start < 1.0
            assert dest.shed_open_total == 1
            assert dest.dropped_total == 1
        finally:
            dest.close()

    def test_sender_failures_open_breaker_and_close_destination(self):
        from veneur_tpu.forward.protos import metric_pb2
        from veneur_tpu.proxy.destinations import Destination

        closed = []
        dest = Destination("127.0.0.1:1", on_close=closed.append,
                           send_buffer=64, flush_interval=0.05,
                           max_consecutive_failures=2)
        try:
            pbm = metric_pb2.Metric(name="x", type=metric_pb2.Counter)
            # two waves so the sender sees two failed batches (a single
            # burst drains into ONE batch = one breaker failure)
            dest.send(pbm)
            deadline = time.time() + 10.0
            while dest.dropped_total < 1 and time.time() < deadline:
                time.sleep(0.05)
            dest.send(pbm)
            while not dest.closed.is_set() and time.time() < deadline:
                time.sleep(0.05)
            assert dest.closed.is_set()
            assert closed and closed[0] is dest
            assert dest.breaker.open_total >= 1
        finally:
            dest.close()

    def test_destinations_telemetry_rows(self):
        from veneur_tpu.proxy.destinations import Destinations

        pool = Destinations()
        pool.set_destinations(["127.0.0.1:1"])
        try:
            rows = pool.telemetry_rows()
            names = {r[0] for r in rows}
            assert "resilience.breaker_state" in names
            assert "proxy.dest.queue_depth" in names
        finally:
            pool.clear()
