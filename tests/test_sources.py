"""OpenMetrics source tests (reference sources/openmetrics tests):
scrape a fake /metrics endpoint, check conversion + counter deltas."""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from veneur_tpu.samplers import metrics as m
from veneur_tpu.sources.openmetrics import OpenMetricsSource, parse_exposition

EXPOSITION_1 = """\
# HELP http_requests_total Total requests.
# TYPE http_requests_total counter
http_requests_total{code="200"} 100
http_requests_total{code="500"} 5
# TYPE temperature gauge
temperature{room="a"} 21.5
# TYPE rpc_duration_seconds summary
rpc_duration_seconds{quantile="0.5"} 0.05
rpc_duration_seconds_sum 17.5
rpc_duration_seconds_count 200
# TYPE request_size histogram
request_size_bucket{le="100"} 30
request_size_bucket{le="+Inf"} 40
request_size_sum 3200
request_size_count 40
untyped_thing 7
"""

EXPOSITION_2 = EXPOSITION_1.replace(
    'http_requests_total{code="200"} 100',
    'http_requests_total{code="200"} 130').replace(
    'http_requests_total{code="500"} 5',
    'http_requests_total{code="500"} 2')  # reset


class FakePrometheus:
    def __init__(self):
        outer = self
        self.body = EXPOSITION_1

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                data = outer.body.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        host, port = self.httpd.server_address
        return f"http://{host}:{port}/metrics"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class CollectingIngest:
    def __init__(self):
        self.metrics = []

    def ingest_metric(self, metric):
        self.metrics.append(metric)

    def by_name(self):
        out = {}
        for metric in self.metrics:
            out.setdefault(metric.name, []).append(metric)
        return out


@pytest.fixture
def fake_prom():
    server = FakePrometheus()
    yield server
    server.close()


class TestParseExposition:
    def test_families(self):
        rows = list(parse_exposition(EXPOSITION_1))
        types = {name: ftype for ftype, name, _, _ in rows}
        assert types["http_requests_total"] == "counter"
        assert types["temperature"] == "gauge"
        assert types["rpc_duration_seconds_sum"] == "summary"
        assert types["request_size_bucket"] == "histogram"
        assert types["untyped_thing"] == "untyped"
        labeled = next(r for r in rows if r[1] == "temperature")
        assert labeled[2] == {"room": "a"}
        assert labeled[3] == 21.5

    def test_escaped_labels(self):
        rows = list(parse_exposition(
            '# TYPE x gauge\nx{msg="say \\"hi\\" now"} 1\n'))
        assert rows[0][2]["msg"] == 'say "hi" now'


class TestOpenMetricsSource:
    def test_counter_delta_and_conversion(self, fake_prom):
        src = OpenMetricsSource("om", url=fake_prom.url, scrape_interval=60)
        ingest = CollectingIngest()

        # first scrape: counters prime the cache, gauges emit immediately
        src.scrape_once(ingest)
        got = ingest.by_name()
        assert "http_requests_total" not in got
        assert got["temperature"][0].value == 21.5
        assert got["temperature"][0].type == m.GAUGE
        assert "room:a" in got["temperature"][0].tags
        assert got["untyped_thing"][0].type == m.GAUGE
        # summary: quantile + sum as gauges; count primes
        assert got["rpc_duration_seconds"][0].value == 0.05
        assert got["rpc_duration_seconds_sum"][0].value == 17.5
        assert "rpc_duration_seconds_count" not in got

        # second scrape: counter deltas (and reset handling)
        fake_prom.body = EXPOSITION_2
        ingest2 = CollectingIngest()
        src.scrape_once(ingest2)
        got2 = ingest2.by_name()
        deltas = {tuple(mm.tags): mm.value
                  for mm in got2["http_requests_total"]}
        assert deltas[("code:200",)] == 30.0
        assert deltas[("code:500",)] == 2.0  # reset -> new value
        assert got2["http_requests_total"][0].type == m.COUNTER
        # unchanged bucket counters emit zero deltas
        buckets = {tuple(mm.tags): mm.value
                   for mm in got2["request_size_bucket"]}
        assert buckets[("le:100",)] == 0.0

    def test_allow_deny(self, fake_prom):
        src = OpenMetricsSource("om", url=fake_prom.url, scrape_interval=60,
                                denylist="^rpc_")
        ingest = CollectingIngest()
        src.scrape_once(ingest)
        assert not any(n.startswith("rpc_") for n in ingest.by_name())

        src2 = OpenMetricsSource("om", url=fake_prom.url, scrape_interval=60,
                                 allowlist="temperature")
        ingest2 = CollectingIngest()
        src2.scrape_once(ingest2)
        assert set(ingest2.by_name()) == {"temperature"}

    def test_extra_tags_and_digest(self, fake_prom):
        src = OpenMetricsSource("om", url=fake_prom.url, scrape_interval=60,
                                tags=["src:om"])
        ingest = CollectingIngest()
        src.scrape_once(ingest)
        temp = ingest.by_name()["temperature"][0]
        assert "src:om" in temp.tags
        assert temp.digest != 0
        assert temp.key.joined_tags == ",".join(sorted(["room:a", "src:om"]))

    def test_server_integration(self, fake_prom):
        from veneur_tpu.config import SourceConfig
        from test_server import generate_config, setup_server
        cfg = generate_config()
        cfg.sources = [SourceConfig(
            kind="openmetrics", name="om",
            config={"url": fake_prom.url, "scrape_interval": "0.05s"})]
        server, observer = setup_server(cfg)
        server.start()
        try:
            import time
            deadline = time.time() + 5
            while time.time() < deadline:
                time.sleep(0.1)
                server.flush()
                try:
                    flushed = observer.wait_flush(timeout=0.5)
                except Exception:
                    continue
                names = {mm.name for mm in flushed}
                if "temperature" in names:
                    break
            else:
                raise AssertionError("scraped gauge never flushed")
        finally:
            server.shutdown()


class TestLabelFiltersAndRenames:
    def test_ignored_and_renamed_labels(self, fake_prom):
        src = OpenMetricsSource(
            "om", url=fake_prom.url, scrape_interval=60,
            ignored_labels=["^ro"], rename_labels={"room": "zone"})
        ingest = CollectingIngest()
        src.scrape_once(ingest)
        temp = ingest.by_name()["temperature"][0]
        # "room" matches the ignored regex, so neither the original nor
        # the renamed label survives
        assert all(not t.startswith("room:") and not t.startswith("zone:")
                   for t in temp.tags)

        src2 = OpenMetricsSource(
            "om", url=fake_prom.url, scrape_interval=60,
            rename_labels={"room": "zone"})
        ingest2 = CollectingIngest()
        src2.scrape_once(ingest2)
        temp2 = ingest2.by_name()["temperature"][0]
        assert "zone:a" in temp2.tags
        assert not any(t.startswith("room:") for t in temp2.tags)

    def test_prometheus_cli_flag_parsing(self, fake_prom, monkeypatch):
        """The reference's short flags (-h/-s/-i/-p/-a/-r/-d) parse and
        build a working source (cmd/veneur-prometheus/main.go:14-28)."""
        import socket as socket_mod

        from veneur_tpu.cmd import veneur_prometheus as vp

        recv = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5.0)
        port = recv.getsockname()[1]

        started = {}

        def fake_start(self, ingest):
            started["source"] = self
            self.scrape_once(ingest)  # gauges emit on first scrape
            raise KeyboardInterrupt

        monkeypatch.setattr(OpenMetricsSource, "start", fake_start)
        rc = vp.main([
            "-h", fake_prom.url, "-s", f"127.0.0.1:{port}",
            "-i", "1s", "-p", "pre.", "-a", "dc=east",
            "-r", "room=zone", "-ignored-metrics", "^rpc_,untyped",
        ])
        assert rc == 0
        src = started["source"]
        assert src.rename_labels == {"room": "zone"}
        assert src.deny.pattern == "^rpc_|untyped"
        # collect whatever the single scrape emitted (counters only
        # prime the cache, denied families are skipped)
        chunks = []
        recv.settimeout(2.0)
        try:
            while True:
                chunks.append(recv.recvfrom(65536)[0])
        except TimeoutError:
            pass
        joined = b" ".join(chunks)
        assert joined.startswith(b"pre.")
        assert b"zone:a" in joined
        assert b"dc:east" in joined
        recv.close()
