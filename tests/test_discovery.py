"""Consul and Kubernetes discoverers against fake HTTP endpoints
(reference discovery/consul/consul.go:30-47 and
discovery/kubernetes/kubernetes.go:34-130), including the proxy ring
following a mutating Consul health list."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from veneur_tpu.proxy.discovery import ConsulDiscoverer, KubernetesDiscoverer


def wait_until(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class _JsonServer:
    """Tiny fake API server; `routes` maps path-prefix -> callable
    returning the JSON payload. Records request headers."""

    def __init__(self):
        self.routes = {}
        self.headers = []
        self.paths = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                outer.headers.append(dict(self.headers))
                outer.paths.append(self.path)
                for prefix, payload_fn in outer.routes.items():
                    if self.path.startswith(prefix):
                        body = json.dumps(payload_fn()).encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                self.send_response(404)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self):
        host, port = self.httpd.server_address
        return f"http://{host}:{port}"

    def close(self):
        self.httpd.shutdown()


def consul_entry(node_addr, port):
    return {"Node": {"Address": node_addr},
            "Service": {"Address": "", "Port": port},
            "Checks": [{"Status": "passing"}]}


class TestConsul:
    def test_healthy_hosts(self):
        srv = _JsonServer()
        srv.routes["/v1/health/service/veneur-global"] = lambda: [
            consul_entry("10.0.0.1", 8128), consul_entry("10.0.0.2", 8128)]
        try:
            disc = ConsulDiscoverer(base_url=srv.url)
            got = disc.get_destinations_for_service("veneur-global")
            assert got == ["10.0.0.1:8128", "10.0.0.2:8128"]
        finally:
            srv.close()

    def test_empty_is_error(self):
        srv = _JsonServer()
        srv.routes["/v1/health/service/"] = lambda: []
        try:
            disc = ConsulDiscoverer(base_url=srv.url)
            with pytest.raises(RuntimeError, match="no hosts"):
                disc.get_destinations_for_service("veneur-global")
        finally:
            srv.close()

    def test_token_header_sent(self):
        srv = _JsonServer()
        srv.routes["/v1/health/service/"] = lambda: [
            consul_entry("10.0.0.1", 1)]
        try:
            disc = ConsulDiscoverer(base_url=srv.url, token="secret-tok")
            disc.get_destinations_for_service("svc")
            assert srv.headers[-1].get("X-Consul-Token") == "secret-tok"
        finally:
            srv.close()

    def test_proxy_ring_follows_mutating_health_list(self):
        """The full elasticity loop: the discovery refresh re-polls the
        fake Consul and the proxy's destination pool follows additions
        and removals (reference proxy/proxy.go discovery loop)."""
        from veneur_tpu.proxy.proxy import ProxyServer

        healthy = [consul_entry("127.0.0.1", 11111)]
        srv = _JsonServer()
        srv.routes["/v1/health/service/"] = lambda: list(healthy)
        proxy = None
        try:
            disc = ConsulDiscoverer(base_url=srv.url)
            proxy = ProxyServer(disc, forward_service="veneur-global",
                                listen_address="127.0.0.1:0",
                                discovery_interval=0.1)
            proxy.start()
            assert wait_until(
                lambda: set(proxy.destinations.addresses())
                == {"127.0.0.1:11111"})
            healthy.append(consul_entry("127.0.0.1", 11112))
            assert wait_until(
                lambda: set(proxy.destinations.addresses())
                == {"127.0.0.1:11111", "127.0.0.1:11112"})
            del healthy[0]
            assert wait_until(
                lambda: set(proxy.destinations.addresses())
                == {"127.0.0.1:11112"})
        finally:
            if proxy is not None:
                proxy.stop()
            srv.close()


def pod(name, ip, phase="Running", ports=({"name": "grpc",
                                           "containerPort": 8128},)):
    return {"metadata": {"name": name},
            "status": {"phase": phase, "podIP": ip},
            "spec": {"containers": [{"ports": list(ports)}]}}


class TestKubernetes:
    def test_grpc_ports_from_running_pods(self):
        srv = _JsonServer()
        srv.routes["/api/v1/pods"] = lambda: {"items": [
            pod("a", "10.1.0.1"),
            pod("b", "10.1.0.2"),
            pod("c", "10.1.0.3", phase="Pending"),
        ]}
        try:
            disc = KubernetesDiscoverer(api_base=srv.url, token="tok")
            got = disc.get_destinations_for_service("ignored")
            assert got == ["10.1.0.1:8128", "10.1.0.2:8128"]
            assert srv.headers[-1].get("Authorization") == "Bearer tok"
        finally:
            srv.close()

    def test_http_and_tcp_only_pods_skipped(self):
        """The reference emitted http:// destinations for these (legacy
        HTTP import); the gRPC-only forward plane skips them so they
        never claim ring keyspace they can't serve."""
        srv = _JsonServer()
        srv.routes["/api/v1/pods"] = lambda: {"items": [
            pod("h", "10.1.0.4",
                ports=({"name": "http", "containerPort": 8127},)),
            pod("t", "10.1.0.5",
                ports=({"protocol": "TCP", "containerPort": 9000},)),
            pod("g", "10.1.0.6",
                ports=({"protocol": "TCP", "containerPort": 9000},
                       {"name": "grpc", "containerPort": 8128},)),
        ]}
        try:
            disc = KubernetesDiscoverer(api_base=srv.url, token="")
            got = disc.get_destinations_for_service("ignored")
            assert got == ["10.1.0.6:8128"]
        finally:
            srv.close()

    def test_pod_without_port_or_ip_skipped(self):
        srv = _JsonServer()
        srv.routes["/api/v1/pods"] = lambda: {"items": [
            pod("nop", "10.1.0.6", ports=()),
            pod("noip", "", ports=({"name": "grpc",
                                    "containerPort": 8128},)),
        ]}
        try:
            disc = KubernetesDiscoverer(api_base=srv.url, token="")
            assert disc.get_destinations_for_service("ignored") == []
        finally:
            srv.close()

    def test_label_selector_in_query(self):
        srv = _JsonServer()
        srv.routes["/api/v1/pods"] = lambda: {"items": []}
        try:
            disc = KubernetesDiscoverer(api_base=srv.url, token="",
                                        label_selector="app=custom")
            disc.get_destinations_for_service("ignored")
            assert "labelSelector=app%3Dcustom" in srv.paths[-1]
        finally:
            srv.close()

    def test_outside_cluster_without_api_base_raises(self, monkeypatch):
        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        with pytest.raises(RuntimeError, match="KUBERNETES_SERVICE_HOST"):
            KubernetesDiscoverer()
