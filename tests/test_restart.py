"""Graceful restart: SIGUSR2 spawns a replacement that overlap-binds via
SO_REUSEPORT; the old process drains and exits only after the
replacement answers /healthcheck/ready (reference einhorn handoff,
server.go:1404, README.md:170-178)."""

from __future__ import annotations

import os
import re
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def ready_pid(port: int):
    """Returns the answering pid, or None when not ready."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthcheck/ready",
                timeout=2) as r:
            if r.status == 200:
                return int(r.headers.get("X-Veneur-Pid", "0"))
    except Exception:
        return None
    return None


class TestInstallContract:
    """restart.install's explicit (shutdown, http_address) contract —
    the seam both CLIs (server and proxy) depend on."""

    def test_ready_handoff_calls_shutdown(self, monkeypatch):
        from veneur_tpu.core import restart

        calls = []

        class FakeChild:
            pid = 4242

            def poll(self):
                return None

        monkeypatch.setattr(restart.subprocess, "Popen",
                            lambda cmd, env=None: FakeChild())
        monkeypatch.setattr(restart, "_wait_ready",
                            lambda addr, child, timeout=0, ready_file="": (
                                calls.append(("ready", addr)) or True))
        restart._restart(lambda: calls.append(("shutdown",)),
                         "127.0.0.1:9999", ["prog"])
        assert ("ready", "127.0.0.1:9999") in calls
        assert ("shutdown",) in calls

    def test_unready_replacement_keeps_the_old_process(self, monkeypatch):
        from veneur_tpu.core import restart

        calls = []

        class FakeChild:
            pid = 4242
            returncode = 1

            def poll(self):
                return 1  # replacement died

        monkeypatch.setattr(restart.subprocess, "Popen",
                            lambda cmd, env=None: FakeChild())
        restart._restart(lambda: calls.append("shutdown"),
                        "127.0.0.1:9999", ["prog"])
        assert calls == []  # old process keeps serving

    def test_no_http_uses_ready_file_handshake(self, tmp_path):
        """Without a readiness endpoint the handoff waits for the
        replacement to write its pid once its listeners are bound — a
        merely-alive child (wedged in startup) must NOT win, and a dead
        child loses immediately."""
        from veneur_tpu.core import restart

        class DeadChild:
            pid = 1111

            def poll(self):
                return 1

        class LiveChild:
            pid = 2222

            def poll(self):
                return None

        rf = str(tmp_path / "ready")
        assert restart._wait_ready("", DeadChild(), timeout=0.3,
                                   ready_file=rf) is False
        # alive but never binds: refused
        assert restart._wait_ready("", LiveChild(), timeout=0.5,
                                   ready_file=rf) is False
        # bound (pid written): handoff proceeds
        with open(rf, "w") as f:
            f.write("2222")
        assert restart._wait_ready("", LiveChild(), timeout=2.0,
                                   ready_file=rf) is True
        # a stale file from some OTHER pid does not count
        with open(rf, "w") as f:
            f.write("9999")
        assert restart._wait_ready("", LiveChild(), timeout=0.5,
                                   ready_file=rf) is False

    def test_restart_ready_file_wedged_child_loses(self, monkeypatch,
                                                   tmp_path):
        """Full _restart coverage of the SIGUSR2 ready-file handoff: a
        child that stays alive but never reports its listeners bound
        (wedged in startup) must NOT win — shutdown is never called, the
        old process keeps serving, and the handshake file is cleaned
        up."""
        from veneur_tpu.core import restart

        spawned = {}

        class WedgedChild:
            pid = 7777

            def poll(self):
                return None  # alive forever, never writes the file

        def fake_popen(cmd, env=None):
            spawned["cmd"], spawned["env"] = cmd, env
            return WedgedChild()

        monkeypatch.setattr(restart.subprocess, "Popen", fake_popen)
        # _restart passes no timeout; bound the real _wait_ready so the
        # wedged child times out in test time, not 60 s
        real_wait = restart._wait_ready
        monkeypatch.setattr(
            restart, "_wait_ready",
            lambda addr, child, ready_file="": real_wait(
                addr, child, timeout=0.6, ready_file=ready_file))
        calls = []
        restart._restart(lambda: calls.append("shutdown"), "", ["prog"])
        assert calls == []  # the old process keeps serving
        # the handshake went through the environment, single-use file
        env = spawned["env"]
        ready_file = env[restart.READY_FILE_ENV]
        assert ready_file.startswith("/") and not os.path.exists(ready_file)

    def test_restart_ready_file_bound_child_wins(self, monkeypatch):
        """The complementary path: a child that writes its pid (its
        Server.start() completed, listeners bound) wins the handoff —
        shutdown runs and the handshake file is removed."""
        from veneur_tpu.core import restart

        spawned = {}

        class BoundChild:
            pid = 8888

            def poll(self):
                # "bind the listeners": write our pid the first time the
                # parent polls us, like Server.start()'s mark_ready()
                rf = spawned["env"][restart.READY_FILE_ENV]
                with open(rf, "w") as f:
                    f.write(str(self.pid))
                return None

        def fake_popen(cmd, env=None):
            spawned["env"] = env
            return BoundChild()

        monkeypatch.setattr(restart.subprocess, "Popen", fake_popen)
        real_wait = restart._wait_ready
        monkeypatch.setattr(
            restart, "_wait_ready",
            lambda addr, child, ready_file="": real_wait(
                addr, child, timeout=5.0, ready_file=ready_file))
        calls = []
        restart._restart(lambda: calls.append("shutdown"), "", ["prog"])
        assert calls == ["shutdown"]
        assert not os.path.exists(spawned["env"][restart.READY_FILE_ENV])

    def test_mark_ready_is_single_use(self, tmp_path, monkeypatch):
        """mark_ready pops the env var: descendants must never inherit
        the handshake path and re-create it later (TOCTOU guard)."""
        from veneur_tpu.core import restart

        rf = tmp_path / "ready"
        monkeypatch.setenv(restart.READY_FILE_ENV, str(rf))
        restart.mark_ready()
        assert rf.read_text() == str(os.getpid())
        assert restart.READY_FILE_ENV not in os.environ
        rf.unlink()
        restart.mark_ready()  # second call: env popped, no-op
        assert not rf.exists()

    def test_server_start_writes_ready_file(self, tmp_path, monkeypatch):
        from veneur_tpu.config import Config
        from veneur_tpu.core.server import Server
        from veneur_tpu.sinks.channel import ChannelMetricSink

        rf = str(tmp_path / "ready")
        monkeypatch.setenv("VENEUR_TPU_READY_FILE", rf)
        cfg = Config()
        cfg.interval = 3600
        cfg.statsd_listen_addresses = ["udp://127.0.0.1:0"]
        cfg.apply_defaults()
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            with open(rf) as f:
                assert f.read().strip() == str(os.getpid())
        finally:
            server.shutdown()


@pytest.mark.skipif(not hasattr(socket, "SO_REUSEPORT"),
                    reason="needs SO_REUSEPORT")
def test_sigusr2_hands_off_without_dropping_the_listener(tmp_path):
    udp_port, http_port = free_port(), free_port()
    cfg = tmp_path / "veneur.yaml"
    cfg.write_text(
        "statsd_listen_addresses:\n"
        f"  - udp://127.0.0.1:{udp_port}\n"
        f"http_address: \"127.0.0.1:{http_port}\"\n"
        "interval: 1.0\n"
        "flush_on_shutdown: true\n"
        "stats_address: \"\"\n"
        "metric_sinks:\n"
        "  - kind: blackhole\n"
        "    name: blackhole\n")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", "")).strip()
    if not env["XLA_FLAGS"]:
        del env["XLA_FLAGS"]
    old = subprocess.Popen(
        [sys.executable, "-m", "veneur_tpu.cmd.veneur", "-f", str(cfg)],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)
    new_pid = None
    try:
        deadline = time.time() + 90
        while time.time() < deadline and ready_pid(http_port) != old.pid:
            assert old.poll() is None, old.stderr.read()[-3000:]
            time.sleep(0.5)
        assert ready_pid(http_port) == old.pid, "server never became ready"

        old.send_signal(signal.SIGUSR2)
        # the replacement must answer ready from a different pid
        deadline = time.time() + 120
        while time.time() < deadline:
            pid = ready_pid(http_port)
            if pid and pid != old.pid:
                new_pid = pid
                break
            time.sleep(0.5)
        assert new_pid, "replacement never became ready"
        # old process drains and exits on its own
        assert old.wait(timeout=60) == 0
        # the port is still served throughout — no listening gap
        deadline = time.time() + 10
        pid = None
        while time.time() < deadline:
            pid = ready_pid(http_port)
            if pid:
                break
            time.sleep(0.2)
        assert pid == new_pid
        # and the UDP listener answers to the new process too: send a
        # packet, then confirm the replacement is still healthy
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.sendto(b"restart.probe:1|c", ("127.0.0.1", udp_port))
        assert ready_pid(http_port) == new_pid
    finally:
        for pid in {new_pid, old.pid if old.poll() is None else None}:
            if pid:
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass
        try:
            old.wait(timeout=10)
        except Exception:
            old.kill()
