"""Auxiliary-subsystem coverage (SURVEY §5): flush watchdog, ConsumePanic
crash reporting, and runtime diagnostics self-metrics."""

import os
import subprocess
import sys
import time

import pytest

from veneur_tpu.core import diagnostics
from veneur_tpu.util import crash

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestConsumePanic:
    """Core report-and-reraise / thread / logging-hook coverage lives in
    tests/test_ops.py TestCrash; only behavior not pinned there is
    added here."""

    def teardown_method(self):
        crash.clear_reporters()

    def test_broken_reporter_does_not_mask_panic(self):
        crash.register_reporter(lambda exc, tb: 1 / 0)
        with pytest.raises(ValueError):
            crash.guarded(lambda: (_ for _ in ()).throw(ValueError("x")))()


class TestDiagnostics:
    def test_collect_emits_runtime_gauges(self):
        calls = []

        class FakeStatsd:
            def gauge(self, name, value, tags=None):
                calls.append((name, value))

            def count(self, name, value, tags=None):
                calls.append((name, value))

        diagnostics.collect(FakeStatsd(), time.time() - 5.0,
                            include_device=False)
        names = {c[0] for c in calls}
        assert {"mem.rss_bytes", "cpu.user_seconds", "threads.count",
                "gc.collections_total", "uptime_ms"} <= names
        by = dict(calls)
        assert by["mem.rss_bytes"] > 0
        assert by["uptime_ms"] >= 5000


class TestFlushWatchdog:
    def test_watchdog_kills_stalled_process(self):
        """Reference server.go:877-919: missed flushes crash the process
        (crash = recovery under a supervisor). Run in a subprocess: a
        flush that hangs forever must lead to os._exit(2)."""
        code = """
import threading, time
from veneur_tpu.config import Config
from veneur_tpu.core.server import Server

cfg = Config()
cfg.interval = 0.3
cfg.flush_watchdog_missed_flushes = 2
cfg.synchronize_with_interval = False
cfg.tpu.counter_capacity = 32
cfg.tpu.gauge_capacity = 32
cfg.tpu.histo_capacity = 32
cfg.tpu.set_capacity = 16
cfg.tpu.batch_cap = 32
cfg.apply_defaults()
server = Server(cfg)
server._flush_locked = lambda: time.sleep(3600)  # simulated stall
server.last_flush_unix = time.time()
server.start()
time.sleep(30)  # watchdog must fire long before this
print("WATCHDOG NEVER FIRED")
"""
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=120,
                              env=env, cwd=REPO)
        assert proc.returncode == 2, (proc.returncode, proc.stderr[-1500:])
        assert "WATCHDOG NEVER FIRED" not in proc.stdout
        # the watchdog dumps tracebacks before exiting (faulthandler)
        assert "watchdog" in proc.stderr.lower()
