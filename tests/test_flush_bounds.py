"""Flush/ingest boundedness: a deliberately hung or slow sink must not
stall the flush loop, kill the process, or starve other sinks — the
TPU-build equivalent of the reference's flush context deadline
(reference server.go:869, flusher.go:553-566) and per-span-sink ingest
timeout (reference worker.go:588-656)."""

import threading
import time

from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink


def _config(**overrides) -> Config:
    cfg = Config()
    cfg.interval = 0.5
    cfg.num_readers = 1
    cfg.statsd_listen_addresses = []
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 256
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


class HungMetricSink:
    """flush() blocks until released (a vendor API that never answers)."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def name(self):
        return "hung"

    def kind(self):
        return "hung"

    def start(self, server):
        pass

    def stop(self):
        pass

    def flush(self, metrics):
        self.calls += 1
        self.release.wait(30.0)

    def flush_other_samples(self, samples):
        pass


class HungSpanSink:
    """ingest() blocks forever; flush() blocks forever."""

    def __init__(self):
        self.release = threading.Event()

    def name(self):
        return "hung_span"

    def kind(self):
        return "hung_span"

    def start(self, server):
        pass

    def stop(self):
        pass

    def ingest(self, span):
        self.release.wait(30.0)

    def flush(self):
        self.release.wait(30.0)


class TestFlushDeadline:
    def test_hung_metric_sink_does_not_stall_flush(self):
        hung = HungMetricSink()
        observer = ChannelMetricSink()
        server = Server(_config(), extra_metric_sinks=[observer, hung])
        try:
            server.handle_metric_packet(b"bound.count:1|c")
            t0 = time.time()
            server.flush()
            # generous slack: the bound under test is "a hung sink's
            # 30s wait cannot stall the flush", not scheduler jitter on
            # a loaded single-CPU host (flake at +1.0)
            assert time.time() - t0 < server.interval + 3.0
            got = {m.name for m in observer.wait_flush()}
            assert "bound.count" in got  # healthy sink still delivered
        finally:
            hung.release.set()

    def test_hung_sink_skipped_on_next_flush(self):
        hung = HungMetricSink()
        observer = ChannelMetricSink()
        server = Server(_config(), extra_metric_sinks=[observer, hung])
        try:
            server.handle_metric_packet(b"bound.a:1|c")
            server.flush()
            assert {m.name for m in observer.wait_flush()} == {"bound.a"}
            assert hung.calls == 1
            server.handle_metric_packet(b"bound.b:1|c")
            t0 = time.time()
            server.flush()
            # previous hung flush still alive -> not re-entered
            assert hung.calls == 1
            assert time.time() - t0 < server.interval + 3.0
            got = {m.name for m in observer.wait_flush()}
            assert "bound.b" in got
        finally:
            hung.release.set()

    def test_hung_span_sink_does_not_stall_span_pipeline(self):
        from veneur_tpu import ssf

        hung = HungSpanSink()
        observer = ChannelMetricSink()
        server = Server(_config(span_channel_capacity=1024),
                        extra_metric_sinks=[observer],
                        extra_span_sinks=[hung])
        server.start()
        try:
            span = ssf.SSFSpan(id=1, trace_id=1, name="op", service="svc",
                               start_timestamp=1, end_timestamp=2)
            span.metrics.append(ssf.count("bound.span.c", 3))
            # many spans: the hung sink's queue fills and drops, but the
            # inline metric extraction keeps working for every span
            for _ in range(200):
                server.ingest_span(ssf.SSFSpan.FromString(
                    span.SerializeToString()))
            deadline = time.time() + 10
            while (not server.span_chan.empty()
                   and time.time() < deadline):
                time.sleep(0.01)
            # chan empty != workers done: the last popped batch may still
            # be mid-extraction; wait for the processed counter to go
            # quiet so its metrics are in the snapshot (suite-load flake)
            last, settled = -1, time.time()
            while time.time() < deadline:
                cur = server.store.processed
                if cur != last:
                    last, settled = cur, time.time()
                elif time.time() - settled > 0.25:
                    break
                time.sleep(0.02)
            server.store.apply_all_pending()
            t0 = time.time()
            server.flush()
            # generous slack: the bound being tested is "a hung sink
            # cannot stall the flush" (it would hang for >= the 10s
            # join grace), not scheduler jitter on a loaded 1-CPU host
            assert time.time() - t0 < server.interval + 3.0
            got = {m.name: m for m in observer.wait_flush()}
            processed = 200 - server.spans_dropped
            assert processed > 0
            assert got["bound.span.c"].value == processed * 3.0
        finally:
            hung.release.set()
            server.shutdown()

    def test_hung_flush_other_samples_does_not_stall_flush(self):
        """Events/service checks are delivered inside each sink's bounded
        flush thread — a vendor events POST that hangs must cost only
        that sink, never the flush loop (it used to run inline)."""
        hung = HungMetricSink()
        hung.flush_other_samples = lambda samples: hung.release.wait(30.0)
        observer = ChannelMetricSink()
        server = Server(_config(), extra_metric_sinks=[observer, hung])
        try:
            # an event (other-sample) plus a metric
            server.handle_metric_packet(
                b"_e{5,4}:title|text|#env:test")
            server.handle_metric_packet(b"bound.ev:1|c")
            t0 = time.time()
            server.flush()
            assert time.time() - t0 < server.interval + 3.0
            got = {m.name for m in observer.wait_flush()}
            assert "bound.ev" in got  # healthy sink still delivered
        finally:
            hung.release.set()

    def test_other_samples_delivered_without_metrics(self):
        """A flush with ONLY events (empty metric batch) still delivers
        them to every sink (the sink threads must start for samples
        alone)."""
        delivered = []

        class EventSink(ChannelMetricSink):
            def flush_other_samples(self, samples):
                delivered.extend(samples)

        sink = EventSink()
        server = Server(_config(), extra_metric_sinks=[sink])
        try:
            server.handle_metric_packet(b"_e{3,2}:abc|de|#k:v")
            deadline = time.time() + 5
            while not delivered and time.time() < deadline:
                server.flush()
                time.sleep(0.05)
            assert delivered, "event never delivered"
        finally:
            server.shutdown()

    def test_flush_timeout_is_counted(self):
        hung = HungMetricSink()
        server = Server(_config(stats_address="internal"),
                        extra_metric_sinks=[hung])
        try:
            server.handle_metric_packet(b"bound.c:1|c")
            server.flush()
            # the self-metric loops back into this server's own pipeline
            server.store.apply_all_pending()
            rows = [meta.name for meta in server.store.counters.meta]
            assert "flush.timeout_total" in rows
        finally:
            hung.release.set()
