"""Elastic resharding tests (the `reshard` marker).

The contract under pin (parallel/reshard.py): a live N -> M reshard
under sustained ingest produces a post-cutover flush BIT-IDENTICAL to a
never-resharded control — all five families; counters exact through the
int64 wire; llhist/HLL registers bit-for-bit; t-digest percentile rows
within re-compression tolerance (pack_centroids_many re-packs the
captured centroids once, statistically identical but not bitwise) — and
`ledger_strict` stays green through every interval including the
cutover one.

Crash coverage: a process death anywhere mid-cutover leaves WAL range
segments behind; a fresh server (ANY topology) replays them
exactly-once and its next flush matches the control. A WAL append fault
degrades only the faulted cell to in-memory merge — still zero loss
absent a crash.

The proxy tier's half: ShardGroupRing.regroup G -> G' keeps every
non-migrating key's owner EXACTLY, converges with a freshly-started
ring at G', and a clean regroup routes zero keys off-range
(`proxy.ring.group_spill` stays 0).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.query import QueryError, QuerySpec, ReshardRetry, \
    parse_tags
from veneur_tpu.core.server import Server
from veneur_tpu.parallel.reshard import ReshardError, migration_cells
from veneur_tpu.proxy.ring import ShardGroupRing
from veneur_tpu.sinks.channel import ChannelMetricSink

pytestmark = pytest.mark.reshard

_FULL = 1 << 64


def wait_until(fn, timeout=120.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def corpus(round_no: int = 0):
    """All five families, enough distinct names to land rows on every
    shard of a small mesh."""
    lines = []
    for i in range(12):
        lines.append(b"rs.c.%d:%d|c|#env:t" % (i, i + 1 + round_no))
        lines.append(b"rs.g.%d:%.2f|g" % (i, i * 1.5 + round_no))
        lines.append(b"rs.t.%d:%.2f|ms" % (i, 10.0 + i + round_no))
        lines.append(b"rs.t.%d:%.2f|ms" % (i, 40.0 + i))
        lines.append(b"rs.s.%d:m%d|s" % (i, i))
        lines.append(b"rs.s.%d:m%d|s" % (i, i + 50 + round_no))
        lines.append(b"rs.ll.%d:%.2f|l" % (i, 3.0 + i + round_no))
    return lines


def mk_server(**kw):
    cfg = Config()
    cfg.interval = 3600.0
    cfg.hostname = "test"
    cfg.statsd_listen_addresses = []
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    cfg.ledger_strict = True
    for k, v in kw.items():
        if "." in k:
            ns, field = k.split(".", 1)
            setattr(getattr(cfg, ns), field, v)
        else:
            setattr(cfg, k, v)
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[obs]), obs


def _feed(server, lines, apply=True):
    for line in lines:
        server.handle_metric_packet(line)
    if apply:
        server.store.apply_all_pending()


def _flushed(metrics):
    return {(m.name, tuple(sorted(m.tags))): float(m.value)
            for m in metrics}


def _assert_bit_identical(resharded: dict, control: dict):
    """Exact equality row for row, except t-digest percentile rows
    (captured centroids are re-compressed ONCE by the migration, so the
    quantile estimate may differ in the last ulps — rtol pins it)."""
    assert set(resharded) == set(control), (
        sorted(set(control) - set(resharded)),
        sorted(set(resharded) - set(control)))
    for key, want in control.items():
        got = resharded[key]
        if key[0].endswith("percentile"):
            assert np.isclose(got, want, rtol=1e-6), (key, got, want)
        else:
            assert got == want, (key, got, want)


def _assert_ledger_clean(server):
    for interval in server.ledger.history_imbalances():
        assert all(v == 0.0 for v in interval.values()), interval
    assert all(v == 0.0 for v in server.ledger.imbalance_net.values())


def _shutdown(server):
    server.config.flush_on_shutdown = False
    server.shutdown()


# ---------------------------------------------------------------------------
# plan geometry
# ---------------------------------------------------------------------------


class TestMigrationCells:
    @pytest.mark.parametrize("n_old,n_new", [
        (2, 3), (3, 2), (2, 4), (4, 2), (3, 5), (8, 3), (1, 2), (5, 5)])
    def test_cells_partition_the_digest_space(self, n_old, n_new):
        """Cells are contiguous, cover [0, 2^64) exactly, number at
        most N+M-1, and every digest inside a cell routes to the cell's
        single old_home / new_home."""
        cells = migration_cells(n_old, n_new)
        assert len(cells) <= n_old + n_new - 1
        assert cells[0]["lo"] == 0
        assert cells[-1]["hi"] == _FULL
        for prev, cur in zip(cells, cells[1:]):
            assert prev["hi"] == cur["lo"]
        rng = np.random.RandomState(7)
        for cell in cells:
            width = cell["hi"] - cell["lo"]
            probes = {cell["lo"], cell["hi"] - 1} | {
                cell["lo"] + int(rng.randint(0, min(width, 1 << 62)))
                for _ in range(8)}
            for d in probes:
                assert (d * n_old) >> 64 == cell["old_home"], (cell, d)
                assert (d * n_new) >> 64 == cell["new_home"], (cell, d)

    def test_identity_reshard_has_no_moving_cells(self):
        for cell in migration_cells(4, 4):
            # same partition on both sides: homes can only agree
            assert cell["old_home"] == cell["new_home"]


# ---------------------------------------------------------------------------
# the cutover itself
# ---------------------------------------------------------------------------


class TestElasticCutover:
    def test_live_split_bit_identity_vs_control(self, tmp_path):
        """2 -> 3 under sustained ingest: rows fed before, DURING, and
        after the reshard all land; the post-cutover flush is
        bit-identical to a never-resharded 2-shard control; strict
        ledger green end to end."""
        server, obs = mk_server(**{"tpu.shards": 2},
                                reshard_spool_dir=str(tmp_path / "wal"))
        control, cobs = mk_server(**{"tpu.shards": 2})
        assert server.store.shard_plane is not None, "virtual mesh missing"
        try:
            _feed(server, corpus(0))
            _feed(control, corpus(0))

            ctl = server.reshard
            ctl.begin(shards=3)
            # sustained ingest while the plan thread prewarms + cuts
            # over: packets keep being admitted (they stage in the
            # ingest ring; the apply below lands them on whichever
            # topology is live — commutative merges make the order
            # immaterial, and the gauge rows' last write is round 1 on
            # both pipelines)
            mid = corpus(1)
            fed = 0
            deadline = time.time() + 300.0
            while ctl.state != "idle" or ctl.epoch == 0:
                assert not ctl.last_error, ctl.last_error
                assert time.time() < deadline, "reshard never finished"
                if fed < len(mid):
                    server.handle_metric_packet(mid[fed])
                    fed += 1
                else:
                    time.sleep(0.01)
            _feed(server, mid[fed:])
            server.store.apply_all_pending()
            _feed(control, mid)

            assert ctl.epoch == 1 and ctl.cutovers == 1
            assert ctl.last_error == ""
            assert ctl.segments_written > 0, "cutover wrote no WAL"
            assert ctl.inflight_metrics() == 0
            assert server.store.shard_plane.n == 3

            # post-split ingest keeps landing on the new plane
            _feed(server, corpus(2))
            _feed(control, corpus(2))

            # the live query plane survived the swap: same answer as
            # the never-resharded control, pre-flush
            spec = QuerySpec.build(metric="rs.c.0", kind="count",
                                   tags=parse_tags("env:t"))
            assert (server.query_plane.query(spec)["value"]
                    == control.query_plane.query(spec)["value"])

            server.flush()
            control.flush()
            _assert_bit_identical(_flushed(obs.drain()),
                                  _flushed(cobs.drain()))
            _assert_ledger_clean(server)
            _assert_ledger_clean(control)
        finally:
            _shutdown(server)
            _shutdown(control)

    def test_crash_mid_cutover_replays_exactly_once(self, tmp_path):
        """Kill the merge after every range segment is durable (the
        widest crash window): a FRESH server — restarted at the OLD
        shard count, not the mid-flight target — replays the segments
        exactly-once and flushes identically to the control."""
        spool_dir = str(tmp_path / "wal")
        server, obs = mk_server(**{"tpu.shards": 2},
                                reshard_spool_dir=spool_dir)
        control, cobs = mk_server(**{"tpu.shards": 2})
        try:
            _feed(server, corpus(0))
            _feed(control, corpus(0))
            ctl = server.reshard

            def die(batch):
                raise RuntimeError("simulated SIGKILL mid-merge")
            ctl._merge_decoded = die

            with pytest.raises(ReshardError, match="SIGKILL"):
                ctl.begin(shards=3, block=True)
            written = ctl.segments_written
            assert written > 0
            assert list((tmp_path / "wal").iterdir()), \
                "no durable segments on disk after the crash"
        finally:
            _shutdown(server)
            del obs

        # restart on the same spool; 2 shards again — recovery must be
        # correct into a topology that differs from the crashed target
        server2, obs2 = mk_server(**{"tpu.shards": 2},
                                  reshard_spool_dir=spool_dir)
        try:
            replayed = server2.reshard.recover()
            assert replayed == written
            assert server2.reshard.replayed_segments == written
            # exactly-once: a second recover finds nothing
            assert server2.reshard.recover() == 0
            server2.flush()
            control.flush()
            _assert_bit_identical(_flushed(obs2.drain()),
                                  _flushed(cobs.drain()))
            _assert_ledger_clean(server2)
        finally:
            _shutdown(server2)
            _shutdown(control)

    @pytest.mark.chaos
    def test_append_fault_degrades_without_loss(self, tmp_path):
        """Every WAL append faulted (chaos seam): the cutover degrades
        to in-memory merge per cell — still zero loss, still
        bit-identical, and the fault is counted loudly."""
        server, obs = mk_server(**{"tpu.shards": 2},
                                reshard_spool_dir=str(tmp_path / "wal"),
                                chaos_enabled=True,
                                chaos_reshard_append_fault_nth=1)
        control, cobs = mk_server(**{"tpu.shards": 2})
        try:
            _feed(server, corpus(0))
            _feed(control, corpus(0))
            server.reshard.begin(shards=3, block=True)
            assert server.reshard.append_faults > 0
            assert server.reshard.segments_written == 0
            assert server.reshard.epoch == 1
            _feed(server, corpus(1))
            _feed(control, corpus(1))
            server.flush()
            control.flush()
            _assert_bit_identical(_flushed(obs.drain()),
                                  _flushed(cobs.drain()))
            _assert_ledger_clean(server)
        finally:
            _shutdown(server)
            _shutdown(control)


# ---------------------------------------------------------------------------
# ready semantics, request validation, query retry
# ---------------------------------------------------------------------------


class TestReadyAndQuerySemantics:
    def test_begin_refuses_unsharded_and_busy(self, tmp_path):
        server, _ = mk_server()  # no mesh
        try:
            with pytest.raises(ReshardError, match="not sharded"):
                server.reshard.begin(shards=2)
        finally:
            _shutdown(server)
        server, _ = mk_server(**{"tpu.shards": 2})
        try:
            with pytest.raises(ReshardError, match=">= 1"):
                server.reshard.begin(shards=0)
            server.reshard.state = "planning"
            try:
                with pytest.raises(ReshardError, match="in progress"):
                    server.reshard.begin(shards=3)
            finally:
                server.reshard.state = "idle"
        finally:
            _shutdown(server)

    def test_ready_degrades_past_deadline(self):
        """/healthcheck/ready flips to 503 + reason while a cutover is
        past its deadline, and recovers the moment the state machine
        returns to idle."""
        server, _ = mk_server(**{"tpu.shards": 2})
        try:
            ok, _reason = server.ready_state()
            assert ok
            server.reshard.state = "cutover"
            server.reshard.deadline_unix = time.time() - 5.0
            ok, reason = server.ready_state()
            assert not ok and "reshard" in reason
            server.reshard.state = "idle"
            server.reshard.deadline_unix = 0.0
            ok, _reason = server.ready_state()
            assert ok
        finally:
            _shutdown(server)

    def test_query_mid_cutover_raises_typed_retry(self):
        """capture() during a cutover returns the typed retry — never a
        shape error from half-swapped generations — and the alert
        engine's per-tick QueryError catch covers it (ReshardRetry IS a
        QueryError, so a topology swap can't crash the alert loop)."""
        assert issubclass(ReshardRetry, QueryError)
        server, _ = mk_server(**{"tpu.shards": 2})
        try:
            _feed(server, corpus(0))
            spec = QuerySpec.build(metric="rs.c.0", kind="count",
                                   tags=parse_tags("env:t"))
            server.reshard.state = "cutover"
            with pytest.raises(ReshardRetry):
                server.query_plane.query(spec)
            # the alert engine path: a tick mid-cutover raises the
            # typed retry, which the loop's `except QueryError` catch
            # swallows (pinned by the issubclass assert above) — the
            # alert loop cannot be crashed by a topology swap
            server.alerts.configure([
                {"id": "r", "metric": "rs.c.0", "kind": "count",
                 "op": ">", "threshold": 0.5, "tags": "env:t"}])
            with pytest.raises(ReshardRetry):
                server.alerts.evaluate_once()
            server.reshard.state = "idle"
            assert server.alerts.evaluate_once() is not None
            assert server.query_plane.query(spec)["value"] is not None
        finally:
            _shutdown(server)

    def test_http_surface(self, tmp_path):
        """POST /reshard kicks a live split (202), /debug/reshard
        reports the state machine, and /query answers 503 + retry while
        a cutover is in flight."""
        from veneur_tpu.core.httpapi import HTTPApi
        server, obs = mk_server(**{"tpu.shards": 2},
                                reshard_spool_dir=str(tmp_path / "wal"))
        api = None
        try:
            _feed(server, corpus(0))
            api = HTTPApi(server.config, server=server,
                          address="127.0.0.1:0")
            api.start()
            host, port = api.address

            def get(path):
                try:
                    with urllib.request.urlopen(
                            f"http://{host}:{port}{path}", timeout=10) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            def post(path, payload):
                req = urllib.request.Request(
                    f"http://{host}:{port}{path}",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=10) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            status, body = get("/debug/reshard")
            assert status == 200
            assert json.loads(body)["state"] == "idle"

            # typed retry through HTTP while a cutover is in flight
            server.reshard.state = "cutover"
            status, body = get("/query?metric=rs.c.0&kind=count&tags=env:t")
            assert status == 503
            payload = json.loads(body)
            assert payload["retry"] is True
            server.reshard.state = "idle"

            status, body = post("/reshard", {"shards": 3})
            assert status == 202, body
            assert json.loads(body)["target_shards"] == 3
            # a second request while one is running is refused
            status, body = post("/reshard", {"shards": 4})
            assert status == 409, body
            assert wait_until(lambda: server.reshard.epoch == 1
                              and server.reshard.state == "idle")
            assert server.store.shard_plane.n == 3
            status, body = get("/debug/reshard")
            assert json.loads(body)["cutovers"] == 1

            status, body = post("/reshard", {"shards": "bogus"})
            assert status == 400
        finally:
            if api is not None:
                api.stop()
            _shutdown(server)

    def test_telemetry_rows_inventory(self):
        """Every reshard.* self-metric in the README inventory is
        emitted by the collector (names drift-pinned here; the
        inventory lint pins the docs side)."""
        server, _ = mk_server(**{"tpu.shards": 2})
        try:
            names = {row[0] for row in server.reshard.telemetry_rows()}
            assert names == {
                "reshard.state", "reshard.epoch", "reshard.cutovers",
                "reshard.last_cutover_seconds",
                "reshard.segments_written", "reshard.replayed_segments",
                "reshard.append_faults", "reshard.capture_failures",
                "reshard.device_losses", "reshard.inflight_metrics"}
        finally:
            _shutdown(server)


# ---------------------------------------------------------------------------
# proxy tier: ShardGroupRing regroup
# ---------------------------------------------------------------------------


def _keys(n=10_000):
    return [f"svc.metric.{i}|host:h{i % 97}" for i in range(n)]


class TestShardGroupRegroup:
    def _ring(self, groups, members, pins=()):
        ring = ShardGroupRing(groups)
        for member, group in pins:
            ring.assign(member, group)
        for member in members:
            ring.add(member)
        return ring

    def test_identity_roundtrip_after_churn(self):
        """G -> G regroup is the identity — even after ejection /
        readmission churn — for pinned AND hash-assigned members."""
        members = [f"10.0.0.{i}:8128" for i in range(9)]
        pins = [(members[i], i % 3) for i in range(4)]
        ring = self._ring(3, members, pins)
        ring.remove(members[2])
        ring.add(members[2])
        before = {k: ring.get(k) for k in _keys()}
        assert ring.regroup(3) == 0
        assert {k: ring.get(k) for k in _keys()} == before

    def test_regroup_converges_with_fresh_ring(self):
        """A regrouped proxy and a freshly-started proxy at G' must
        agree on every key — the fleet regroups without coordination,
        so both derivations of (address -> group) must match."""
        members = [f"10.0.1.{i}:8128" for i in range(10)]
        pins = [(members[0], 2), (members[1], 5)]
        ring = self._ring(3, members, pins)
        moved = ring.regroup(5)
        fresh = self._ring(5, members, pins)
        assert moved >= 0
        keys = _keys()
        assert [ring.get(k) for k in keys] == [fresh.get(k) for k in keys]

    def test_nonmigrating_keys_keep_owner_exactly(self):
        """The sticky-assignment pin: across G=3 -> G'=4, every key
        whose new group's member set equals its old group's member set
        keeps its owner EXACTLY (ring points are a pure function of
        group membership). Members are pinned to groups 0..2, which
        survive the widening unchanged — so the property provably
        bites on the whole first quarter of the digest space."""
        members = [f"10.0.2.{i}:8128" for i in range(12)]
        ring = self._ring(3, members,
                          pins=[(m, i % 3)
                                for i, m in enumerate(members)])
        old_sets = {g: set(ms) for g, ms in
                    enumerate(ring.group_members())}
        keys = _keys()
        before = {}
        for k in keys:
            p = ring.point_of(k)
            before[k] = (ring.group_of_point(p), ring.get_at(p))
        ring.regroup(4)
        new_sets = {g: set(ms) for g, ms in
                    enumerate(ring.group_members())}
        checked = 0
        for k in keys:
            p = ring.point_of(k)
            old_group, old_owner = before[k]
            if new_sets[ring.group_of_point(p)] == old_sets[old_group]:
                assert ring.get_at(p) == old_owner, k
                checked += 1
        # the property must actually bite on a real fraction of keys
        assert checked > len(keys) // 20, checked

    def test_clean_regroup_is_spill_free(self):
        """After a regroup that leaves every group populated, no key
        routes off-range: the pool's group_spill counter stays 0 over
        10k routed points."""
        from veneur_tpu.proxy.destinations import Destinations
        pool = Destinations(shard_groups=3)
        members = [f"10.0.3.{i}:8128" for i in range(12)]
        for m in members:
            pool.ring.add(m)
        moved = pool.regroup(4)
        assert pool.shard_groups == 4 and pool.ring.groups == 4
        assert all(pool.ring.group_members()), \
            "regroup left an empty group; spill check would be vacuous"
        for k in _keys():
            point = pool.ring.point_of(k)
            with pool._lock:
                pool._note_group_spill(point, pool.ring.get_at(point))
        assert pool.group_spill_total == 0
        assert moved >= 0

    def test_regroup_refuses_flat_ring(self):
        from veneur_tpu.proxy.destinations import Destinations
        pool = Destinations(shard_groups=0)  # plain ConsistentRing
        with pytest.raises(ValueError):
            pool.regroup(4)


# ---------------------------------------------------------------------------
# SIGKILL soak: the real kill -9 mid-cutover loop (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestReshardSoak:
    def test_sigkill_mid_cutover_soak(self):
        """Drive scripts/reshard_soak.py: SIGKILL a real mesh child
        mid-cutover (range segments durable, merge held open in the
        chaos seam), restart at the OLD shard count, replay — the
        flush diffs clean against the never-resharded control."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "reshard_soak",
            os.path.join(os.path.dirname(__file__), os.pardir,
                         "scripts", "reshard_soak.py"))
        soak = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(soak)
        report = soak.run_soak(kills=1)
        assert report["kills"] == 1 and report["restarts"] == 1
        # nonempty and already diffed bit-identical inside run_soak
        assert all(r["rows"] > 0 for r in report["rounds"])
