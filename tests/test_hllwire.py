"""axiomhq HLL wire-format interop (reference samplers.go:299-311,
vendor/github.com/axiomhq/hyperloglog): dense round trips, tailcut
clamping, sparse decoding via encode/decode hash parity, and the forward
plane accepting/emitting the format."""

import numpy as np
import pytest

from veneur_tpu.forward import hllwire
from veneur_tpu.ops import hll_ref


class TestDense:
    def test_round_trip_small_values(self):
        rng = np.random.default_rng(7)
        regs = rng.integers(0, 16, hll_ref.M).astype(np.uint8)
        data = hllwire.marshal_dense(regs)
        assert data[0] == 1 and data[1] == 14 and data[3] == 0
        assert len(data) == 8 + hll_ref.M // 2
        back, p = hllwire.unmarshal(data)
        assert p == 14
        np.testing.assert_array_equal(back, regs)

    def test_clamps_above_tailcut_range(self):
        regs = np.zeros(hll_ref.M, np.uint8)
        regs[5] = 40  # rho can reach 51 at p=14; the wire caps at 15
        back, _ = hllwire.unmarshal(hllwire.marshal_dense(regs))
        assert back[5] == 15
        assert back[4] == 0

    def test_base_offset_round_trip(self):
        # every register occupied and min > 0: marshal uses the base the
        # way Go's rebase would, unmarshal adds it back
        regs = np.full(hll_ref.M, 18, np.uint8)
        regs[0] = 3
        data = hllwire.marshal_dense(regs)
        assert data[2] == 3  # base = min(minv, maxv - 15)
        back, _ = hllwire.unmarshal(data)
        assert back[0] == 3
        assert back[1] == 18

    def test_estimate_preserved(self):
        h = hll_ref.HLL()
        for i in range(5000):
            h.insert(b"member-%d" % i)
        back, _ = hllwire.unmarshal(hllwire.marshal_dense(h.regs))
        est = hll_ref.estimate_from_registers(back.astype(np.int8))
        assert est == pytest.approx(5000, rel=0.03)

    def test_size_mismatch_rejected(self):
        with pytest.raises(hllwire.HLLWireError):
            hllwire.unmarshal(bytes([1, 14, 0, 0]) + b"\x00\x00\x00\x05" + b"x" * 5)


class TestSparse:
    def test_encode_decode_hash_parity(self):
        rng = np.random.default_rng(11)
        for _ in range(500):
            x = int(rng.integers(0, 2**63)) << 1 | int(rng.integers(0, 2))
            idx, rho = hll_ref.pos_val(x)
            k = hllwire.encode_hash(x)
            didx, drho = hllwire.decode_hash(k)
            assert didx == idx
            assert drho == rho  # the sparse encoding is exact

    def test_sparse_payload_decodes(self):
        """Hand-build a sparse sketch (tmpSet + compressed list) exactly as
        the Go marshaller lays it out and check register parity."""
        rng = np.random.default_rng(13)
        hashes = [int(rng.integers(0, 2**63)) * 2 + 1 for _ in range(64)]
        keys = sorted({hllwire.encode_hash(x) for x in hashes})
        half = len(keys) // 2
        tmp_set, listed = keys[:half], keys[half:]

        payload = bytearray((1, 14, 0, 1))
        payload += len(tmp_set).to_bytes(4, "big")
        for k in tmp_set:
            payload += k.to_bytes(4, "big")
        # compressed list: count, last, varint deltas of the sorted keys
        var = bytearray()
        last = 0
        for k in listed:
            delta = k - last
            while delta & ~0x7F:
                var.append((delta & 0x7F) | 0x80)
                delta >>= 7
            var.append(delta)
            last = k
        payload += len(listed).to_bytes(4, "big")
        payload += (listed[-1] if listed else 0).to_bytes(4, "big")
        payload += len(var).to_bytes(4, "big")
        payload += bytes(var)

        regs, p = hllwire.unmarshal(bytes(payload))
        assert p == 14
        want = np.zeros(hll_ref.M, np.uint8)
        for k in keys:
            idx, r = hllwire.decode_hash(k)
            want[idx] = max(want[idx], r)
        np.testing.assert_array_equal(regs, want)


class TestSparseMarshal:
    def test_round_trip_exact(self):
        """marshal_sparse -> unmarshal reproduces the registers exactly,
        including rho values in both key formats (<=pp-p packs the rank
        in the remainder bits; larger rho uses the explicit form)."""
        regs = np.zeros(hll_ref.M, np.uint8)
        rng = np.random.default_rng(7)
        idxs = rng.choice(hll_ref.M, 300, replace=False)
        regs[idxs[:150]] = rng.integers(1, 12, 150)    # LSB=0 form
        regs[idxs[150:]] = rng.integers(12, 51, 150)   # LSB=1 form
        blob = hllwire.marshal_sparse(regs)
        got, p = hllwire.unmarshal(blob)
        assert p == 14
        np.testing.assert_array_equal(got, regs)

    def test_matches_go_member_hash_path(self):
        """Registers built from real member hashes (the Go insert path,
        encode_hash) survive the sparse round trip bit-for-bit."""
        rng = np.random.default_rng(17)
        regs = np.zeros(hll_ref.M, np.uint8)
        for _ in range(400):
            x = int(rng.integers(0, 2**63)) << 1 | int(rng.integers(0, 2))
            idx, rho = hll_ref.pos_val(x)
            regs[idx] = max(regs[idx], rho)
        got, _ = hllwire.unmarshal(hllwire.marshal_sparse(regs))
        np.testing.assert_array_equal(got, regs)

    def test_small_set_is_small(self):
        """VERDICT bar: a 10-member set serializes in <100 bytes vs the
        ~8 KB dense form."""
        regs = np.zeros(hll_ref.M, np.uint8)
        rng = np.random.default_rng(3)
        for _ in range(10):
            x = int(rng.integers(0, 2**63))
            idx, rho = hll_ref.pos_val(x)
            regs[idx] = max(regs[idx], rho)
        blob = hllwire.marshal(regs)
        assert blob[3] == 1  # sparse form chosen
        assert len(blob) < 100, len(blob)
        got, _ = hllwire.unmarshal(blob)
        np.testing.assert_array_equal(got, regs)

    def test_crossover_prefers_smaller(self):
        rng = np.random.default_rng(5)
        for nnz in (0, 1, 500, 1600, 1700, 8000, hll_ref.M):
            regs = np.zeros(hll_ref.M, np.uint8)
            if nnz:
                idxs = rng.choice(hll_ref.M, nnz, replace=False)
                regs[idxs] = rng.integers(1, 30, nnz)
            blob = hllwire.marshal(regs)
            alt = (hllwire.marshal_dense(regs) if blob[3] == 1
                   else hllwire.marshal_sparse(regs))
            assert len(blob) <= len(alt), (nnz, len(blob), len(alt))
            got, _ = hllwire.unmarshal(blob)
            # dense clamps to the 4-bit tailcut range; sparse is exact
            if blob[3] == 1:
                np.testing.assert_array_equal(got, regs)

    def test_oversized_rho_falls_back_to_dense(self):
        """A rho beyond pp-p+63 (possible after merging a based dense
        import) would overflow the sparse 6-bit rank field; marshal must
        route such registers through the dense/base encoding instead of
        emitting corrupt keys."""
        regs = np.zeros(hll_ref.M, np.int16)
        regs[:] = 70                 # base floor so dense b > 0
        regs[5] = 80                 # > 11 + 63
        blob = hllwire.marshal(regs.astype(np.uint8))
        assert blob[3] == 0          # dense chosen
        got, _ = hllwire.unmarshal(blob)
        assert int(got[5]) > int(got[6])  # ordering survives the base

    def test_empty_set_round_trips(self):
        regs = np.zeros(hll_ref.M, np.uint8)
        blob = hllwire.marshal(regs)
        got, _ = hllwire.unmarshal(blob)
        assert got.sum() == 0


class TestForwardPlane:
    def test_import_server_accepts_axiomhq_payload(self):
        from veneur_tpu.forward.server import _decode_hll

        h = hll_ref.HLL()
        for i in range(200):
            h.insert(b"x%d" % i)
        data = hllwire.marshal_dense(h.regs)
        regs = _decode_hll(data)
        assert regs is not None
        est = hll_ref.estimate_from_registers(regs)
        assert est == pytest.approx(200, rel=0.1)

    def test_import_server_still_accepts_raw_dump(self):
        from veneur_tpu.forward.server import _decode_hll

        raw = np.zeros(hll_ref.M, np.int8)
        raw[7] = 9
        regs = _decode_hll(raw.tobytes())
        np.testing.assert_array_equal(regs, raw)

    def test_convert_emits_axiomhq(self):
        from veneur_tpu.core.columnstore import RowMeta
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.convert import forwardable_to_protos
        from veneur_tpu.samplers.metrics import MetricScope

        regs = np.zeros(hll_ref.M, np.uint8)
        regs[3] = 5
        meta = RowMeta(name="s.x", tags=["a:b"], joined_tags="a:b",
                       digest32=1, scope=MetricScope.MIXED, wire_type="set")
        fwd = ForwardableState()
        fwd.sets.append((meta, regs))
        protos = forwardable_to_protos(fwd)
        payload = protos[0].set.hyper_log_log
        back, p = hllwire.unmarshal(payload)
        assert p == 14
        np.testing.assert_array_equal(back, regs)

    def test_end_to_end_forward_merges_sets(self):
        """Local -> import server -> global merge over the real gRPC plane
        with the axiomhq payload on the wire."""
        from veneur_tpu.config import Config
        from veneur_tpu.core.server import Server
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.forward.server import ImportServer
        from veneur_tpu.sinks.channel import ChannelMetricSink

        def mk(**kw):
            cfg = Config()
            cfg.interval = 60.0
            cfg.statsd_listen_addresses = []
            cfg.tpu.counter_capacity = 64
            cfg.tpu.gauge_capacity = 64
            cfg.tpu.histo_capacity = 64
            cfg.tpu.set_capacity = 64
            cfg.tpu.batch_cap = 64
            for k, v in kw.items():
                setattr(cfg, k, v)
            cfg.apply_defaults()
            obs = ChannelMetricSink()
            return Server(cfg, extra_metric_sinks=[obs]), obs

        glob, gobs = mk()
        imp = ImportServer(glob, "127.0.0.1:0")
        imp.start()
        try:
            local, _ = mk(forward_address=imp.address)
            client = ForwardClient(imp.address, deadline=10.0)
            local.forwarder = client.forward
            for i in range(120):
                local.handle_metric_packet(b"fwd.hll.set:u%d|s" % (i % 97))
            local.store.apply_all_pending()
            local.flush()
            client.close()
            glob.store.apply_all_pending()
            glob.flush()
            got = {m.name: m for m in gobs.wait_flush()}
            assert got["fwd.hll.set"].value == pytest.approx(97, rel=0.05)
        finally:
            imp.stop()
