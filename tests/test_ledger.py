"""Flow-ledger tests (core/ledger.py): double-entry unit semantics,
the server's ingest/forward/forward_tier conservation identities under
real flushes, the chaos_ledger_leak silent-drop drill (the acceptance
pin: caught within one flush interval), the /debug/ledger HTTP surface
on server and proxy, the proxy's churn-proof egress books, the
flow_report pretty-printer, and the slow-marked <2% overhead soak."""

import json
import time
import urllib.request

import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.ledger import FlowLedger, LedgerImbalance
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink
from veneur_tpu.testing.forwardtest import ForwardTestServer


def make_config(**overrides) -> Config:
    cfg = Config()
    cfg.hostname = "test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


def wait_until(fn, timeout=10.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def imbalances(server):
    rep = server.ledger.report()
    return {k: v["imbalance_net"] for k, v in rep["identities"].items()}


# -------------------------------------------------------------------------
# FlowLedger unit semantics
# -------------------------------------------------------------------------


class TestFlowLedgerUnit:
    def test_balanced_identity_closes_clean(self):
        led = FlowLedger(strict=True)
        led.declare("x", inputs=("in",), outputs=("out_a", "out_b"))
        led.note("in", 10)
        led.note("out_a", 7)
        led.note("out_b", 3)
        rec = led.close_interval()
        assert rec["imbalance"]["x"] == 0.0
        assert led.intervals_closed == 1

    def test_imbalance_detected_and_event_fired(self):
        events = []
        led = FlowLedger(on_event=lambda kind, **f: events.append((kind, f)))
        led.declare("x", inputs=("in",), outputs=("out",))
        led.note("in", 10)
        led.note("out", 6)
        rec = led.close_interval()
        assert rec["imbalance"]["x"] == 4.0
        assert led.imbalance_last["x"] == 4.0
        assert led.unexplained_total["x"] == 4.0
        assert events and events[0][0] == "ledger_imbalance"
        assert events[0][1]["imbalance"]["x"] == 4.0

    def test_strict_raises(self):
        led = FlowLedger(strict=True)
        led.declare("x", inputs=("in",), outputs=("out",))
        led.note("in", 1)
        with pytest.raises(LedgerImbalance) as ei:
            led.close_interval()
        assert ei.value.imbalances["x"] == 1.0

    def test_disabled_is_inert(self):
        led = FlowLedger(enabled=False, strict=True)
        led.declare("x", inputs=("in",), outputs=("out",))
        led.note("in", 5)
        assert led.close_interval() == {}
        assert led.telemetry_rows() == []

    def test_probe_folds_deltas_not_baseline(self):
        led = FlowLedger()
        led.declare("x", inputs=("in",), outputs=("out",))
        counter = {"v": 100.0}  # pre-existing count: not interval 1's
        led.probe("in", lambda: counter["v"])
        led.note("out", 0)
        rec = led.close_interval()
        assert rec["imbalance"]["x"] == 0.0  # baseline absorbed
        counter["v"] += 3
        led.note("out", 3)
        rec = led.close_interval()
        assert rec["imbalance"]["x"] == 0.0
        assert rec["stages"]["in"][""] == 3.0

    def test_probe_map_per_key_deltas(self):
        led = FlowLedger()
        table = {"a|x": 2}
        led.probe_map("shed", lambda: table)
        table["a|x"] = 5
        table["b"] = 1
        rec = led.close_interval()
        assert rec["stages"]["shed"] == {"a|x": 3.0, "b": 1.0}

    def test_stock_inventory_balances_across_intervals(self):
        led = FlowLedger(strict=True)
        led.declare("x", inputs=("in",), outputs=("out",), stocks=("q",))
        level = {"v": 0.0}
        led.stock("q", lambda: level["v"])
        # interval 1: 10 in, 4 out, 6 still queued
        led.note("in", 10)
        led.note("out", 4)
        level["v"] = 6.0
        assert led.close_interval()["imbalance"]["x"] == 0.0
        # interval 2: nothing new, the queue drains
        led.note("out", 6)
        level["v"] = 0.0
        assert led.close_interval()["imbalance"]["x"] == 0.0

    def test_preexisting_stock_is_opening_not_inflow(self):
        led = FlowLedger(strict=True)
        led.declare("x", inputs=("in",), outputs=("out",), stocks=("q",))
        level = {"v": 5.0}  # e.g. spool segments replayed at startup
        led.stock("q", lambda: level["v"])
        level["v"] = 0.0
        led.note("out", 5)  # drained without any inflow this interval
        assert led.close_interval()["imbalance"]["x"] == 0.0

    def test_history_bounded_and_report_shape(self):
        led = FlowLedger(history=3)
        led.declare("x", inputs=("in",), outputs=("out",))
        for i in range(5):
            led.note("in", i)
            led.note("out", i)
            led.close_interval()
        rep = led.report()
        assert len(rep["intervals"]) == 3
        assert rep["intervals_closed"] == 5
        assert rep["identities"]["x"]["imbalance_net"] == 0.0
        assert led.report(intervals=1)["intervals"][-1]["interval"] == 5

    def test_telemetry_rows_names_match_declared(self):
        from veneur_tpu.core.ledger import LEDGER_ROWS
        led = FlowLedger()
        led.declare("x", inputs=("in",), outputs=("out",))
        led.note("in", 1)
        led.note("out", 1)
        led.stock("q", lambda: 2.0)
        led.close_interval()
        names = {row[0] for row in led.telemetry_rows()}
        assert names <= set(LEDGER_ROWS)
        assert "ledger.imbalance" in names
        assert "ledger.stage_total" in names
        assert "ledger.stock" in names


# -------------------------------------------------------------------------
# Server integration: the conservation identities under real flushes
# -------------------------------------------------------------------------


class TestServerIngestIdentity:
    def test_mixed_families_balance_strict(self):
        server = Server(make_config(ledger_strict=True))
        # determinism: each flush self-span rolls a 1% chance of an
        # ssf.names_unique SET sample, which would land one extra
        # admitted python sample and break the exact count below
        server.metric_extraction._uniqueness_rate = 0.0
        server.start()
        try:
            for i in range(7):
                server.handle_metric_packet(b"led.c:2|c")
                server.handle_metric_packet(b"led.g:%d|g" % i)
                server.handle_metric_packet(b"led.h:1.5|h")
                server.handle_metric_packet(b"led.s:m%d|s" % i)
                server.handle_metric_packet(b"led.l:%d|l" % (i + 1))
                server.handle_metric_packet(b"_sc|led.sc|0")
            server.flush()  # strict: raises on any imbalance
            rep = server.ledger.report()
            applied = rep["stage_totals"]["agg.applied"]
            assert applied["counter"] == 7
            assert applied["status"] == 7
            assert rep["stage_totals"]["ingest.admitted"]["python"] == 42
        finally:
            server.shutdown()

    def test_mint_rejection_is_explained(self):
        cfg = make_config(ledger_strict=True)
        cfg.tpu.max_rows_per_family = 2
        server = Server(cfg)
        server.start()
        try:
            for i in range(6):
                server.handle_metric_packet(b"cap.k%d:1|c" % i)
            server.flush()  # strict: the capped mints must be explained
            rep = server.ledger.report()
            assert rep["stage_totals"]["agg.rejected"]["counter"] == 4.0
            assert rep["stage_totals"]["agg.applied"]["counter"] == 2.0
        finally:
            server.shutdown()

    def test_parse_errors_ride_along_informationally(self):
        server = Server(make_config(ledger_strict=True))
        server.start()
        try:
            server.handle_metric_packet(b"garbage")
            server.handle_metric_packet(b"ok.c:1|c")
            server.flush()
            rep = server.ledger.report()
            assert rep["stage_totals"]["ingress.parse_errors"][""] == 1.0
        finally:
            server.shutdown()


class TestLeakDrill:
    """The acceptance pin: a deliberately injected SILENT drop (the
    chaos_ledger_leak seam — no shed accounting at all) is caught as a
    nonzero ledger.imbalance within one flush interval."""

    def test_leak_caught_within_one_interval(self):
        server = Server(make_config(
            chaos_enabled=True, chaos_ledger_leak=3))
        server.start()
        try:
            for _ in range(9):
                server.handle_metric_packet(b"leak.c:1|c")
            server.flush()
            rep = server.ledger.report()
            leaked = server.chaos.leaked_samples
            assert leaked == 3
            assert rep["identities"]["ingest"]["imbalance_last"] == leaked
            # the flight recorder saw it
            events = server.telemetry.events.snapshot(
                kind="ledger_imbalance")
            assert events
            assert events[-1]["imbalance"]["ingest"] == leaked
            # and the gauges export it
            rows = {(r[0], tuple(r[3])): r[2]
                    for r in server.ledger.telemetry_rows()}
            assert rows[("ledger.imbalance",
                         ("identity:ingest",))] == leaked
        finally:
            server.shutdown()

    def test_leak_raises_in_strict_mode(self):
        server = Server(make_config(
            ledger_strict=True, chaos_enabled=True, chaos_ledger_leak=2))
        server.start()
        try:
            for _ in range(4):
                server.handle_metric_packet(b"leak.c:1|c")
            with pytest.raises(LedgerImbalance):
                server.flush()
        finally:
            server.shutdown()


class TestForwardIdentity:
    def test_fault_then_drain_balances_every_interval(self):
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        server = None
        try:
            server = Server(make_config(
                forward_address=ft.address, ledger_strict=True,
                chaos_enabled=True, chaos_error_rate=1.0,
                chaos_seams=["forward_send"], chaos_seed=3,
                forward_retry_max_attempts=1,
                carryover_max_intervals=1000,
                circuit_breaker_failure_threshold=10_000))
            # determinism: each flush self-span rolls a 1% chance of an
            # ssf.names_unique SET sample (global scope — it would
            # forward and intermittently become a second carryover row,
            # breaking the exact row-count assert below)
            server.metric_extraction._uniqueness_rate = 0.0
            server.start()
            for i in range(3):
                server.handle_metric_packet(
                    b"fwd.c:%d|c|#veneurglobalonly" % (i + 1))
                server.flush()  # strict: every faulted interval balances
                # settle the per-sink flush threads before the next
                # manual flush: on a loaded host an in-flight forward
                # send overlapping the next snapshot re-adds its failed
                # rows AFTER that flush drained the carryover, leaving
                # two same-key rows until the interval after
                assert wait_until(lambda: all(
                    not t.is_alive()
                    for t in server._sink_flush_threads.values()))
            # stocks hold the undelivered state
            assert server.ledger.report()["stocks"][
                "forward_carryover"] == 1  # same key merged down to 1 row
            server.chaos.enabled = False
            server.flush()
            assert wait_until(
                lambda: server.forward_client.carryover.depth == 0)
            rep = server.ledger.report()
            assert all(v == 0.0 for v in imbalances(server).values())
            assert rep["stage_totals"]["forward.acked"][""] >= 1
            assert rep["stage_totals"]["forward.merged_away"]["drain"] >= 1
        finally:
            if server is not None:
                server.shutdown()
            ft.stop()

    def test_tier_reconciliation_against_real_global(self):
        global_server = Server(make_config(
            grpc_address="127.0.0.1:0", ledger_strict=True))
        global_server.start()
        local = None
        try:
            local = Server(make_config(
                forward_address=global_server.import_server.address,
                ledger_strict=True))
            local.start()
            local.handle_metric_packet(b"tier.c:5|c|#veneurglobalonly")
            local.handle_metric_packet(b"tier.l:2|l")
            local.flush()
            rep = local.ledger.report()
            totals = rep["stage_totals"]
            # the global's FlowCounts response reconciled sent == merged
            assert totals["forward.acked_reported"][""] == 2.0
            assert totals["forward.remote_merged"][""] == 2.0
            assert "forward.remote_rejected" not in totals
            # the global's own ingest identity balances on its flush
            global_server.flush()
            g = global_server.ledger.report()
            assert g["stage_totals"]["ingest.admitted"]["forward"] == 2.0
            assert g["stage_totals"]["import.received"]["forward"] == 2.0
        finally:
            if local is not None:
                local.shutdown()
            global_server.shutdown()


# -------------------------------------------------------------------------
# HTTP surface + proxy books
# -------------------------------------------------------------------------


class TestLedgerHTTP:
    def test_debug_ledger_endpoint(self):
        server = Server(make_config(http_address="127.0.0.1:0"))
        server.start()
        try:
            server.handle_metric_packet(b"http.c:1|c")
            server.flush()
            host, port = server.http_api.address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/debug/ledger?intervals=1") as r:
                body = json.loads(r.read())
            assert body["intervals_closed"] >= 1
            assert "ingest" in body["identities"]
            assert len(body["intervals"]) == 1
            assert body["intervals"][-1]["imbalance"]["ingest"] == 0.0
        finally:
            server.shutdown()


class TestProxyLedger:
    def _proxy(self, addresses, **kwargs):
        from veneur_tpu.proxy.proxy import create_static_proxy
        proxy = create_static_proxy(
            addresses, health_check_interval=0, **kwargs)
        proxy.start()
        return proxy

    def test_egress_books_survive_destination_churn(self):
        from veneur_tpu.forward.protos import metric_pb2
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        proxy = self._proxy([ft.address], ledger_strict=True)
        try:
            for i in range(10):
                proxy.handle_metric(metric_pb2.Metric(
                    name=f"p.{i}", tags=["a:b"],
                    type=metric_pb2.Counter, scope=metric_pb2.Global,
                    counter=metric_pb2.CounterValue(value=i)))
            proxy.destinations.flush_wait(timeout=5.0)
            assert wait_until(lambda:
                              proxy.destinations.flow_totals()["sent"] == 10)
            proxy.ledger.close_interval()  # strict: must balance
            before = proxy.destinations.flow_totals()
            assert before["enqueued"] == 10
            # churn: drop the destination; its counters must FOLD into
            # the retired totals, not vanish (satellite: retired_* fold)
            proxy.destinations.set_destinations(["127.0.0.1:1"])
            after = proxy.destinations.flow_totals()
            assert after["enqueued"] >= before["enqueued"]
            assert after["sent"] >= before["sent"]
            proxy.ledger.close_interval()  # still balanced after churn
            rep = proxy.ledger.report()
            assert rep["identities"]["proxy_egress"]["imbalance_net"] == 0.0
            # tier reconciliation columns exist only for upgraded
            # receivers; the stub answers empty — unreported, no rows
            assert "dest.acked_reported" not in rep["stage_totals"]
        finally:
            proxy.stop()
            ft.stop()

    def test_proxy_route_identity_balances(self):
        from veneur_tpu.forward.protos import metric_pb2
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        proxy = self._proxy([ft.address], ledger_strict=True)
        try:
            for i in range(6):
                proxy.handle_metric(metric_pb2.Metric(
                    name="route.x", tags=[],
                    type=metric_pb2.Counter, scope=metric_pb2.Global,
                    counter=metric_pb2.CounterValue(value=1)))
            proxy.ledger.close_interval()
            rep = proxy.ledger.report()
            assert rep["identities"]["proxy_route"]["imbalance_net"] == 0.0
            assert rep["stage_totals"]["proxy.received"][""] == 6.0
        finally:
            proxy.stop()
            ft.stop()


# -------------------------------------------------------------------------
# flow_report script
# -------------------------------------------------------------------------


class TestFlowReportScript:
    def _mod(self):
        import importlib.util
        import os
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "flow_report.py")
        spec = importlib.util.spec_from_file_location("flow_report", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_format_and_exit_codes(self, tmp_path, capsys):
        mod = self._mod()
        server = Server(make_config())
        server.start()
        try:
            server.handle_metric_packet(b"rep.c:1|c")
            server.flush()
            report = server.ledger.report()
        finally:
            server.shutdown()
        text = mod.format_report(report)
        assert "flow ledger" in text
        assert "ingest" in text and "forward_tier" in text
        assert "** UNEXPLAINED **" not in text
        # saved-JSON mode drives the same path the live-URL mode uses
        path = tmp_path / "ledger.json"
        path.write_text(json.dumps(report))
        assert mod.main([str(path)]) == 0
        capsys.readouterr()
        # a doctored leak flips the exit code — keyed off the lifetime
        # |imbalance| sum, so opposite-sign leaks can't self-cancel
        # into a clean exit (imbalance_net stays 0 here on purpose)
        report["identities"]["ingest"]["unexplained_total"] = 6.0
        path.write_text(json.dumps(report))
        assert mod.main([str(path)]) == 1


# -------------------------------------------------------------------------
# Overhead soak (acceptance: <2% of flush wall time, strict off)
# -------------------------------------------------------------------------


@pytest.mark.slow
class TestLedgerOverheadSoak:
    N_KEYS = 1500
    ROUNDS = 30

    def _median_flush_s(self, ledger_on: bool) -> float:
        cfg = make_config(ledger_enabled=ledger_on)
        cfg.tpu.counter_capacity = 4096
        cfg.tpu.gauge_capacity = 4096
        cfg.tpu.histo_capacity = 4096
        cfg.tpu.set_capacity = 1024
        server = Server(cfg)
        server.start()
        pkts = []
        for i in range(self.N_KEYS):
            kind = i % 4
            if kind == 0:
                pkts.append(b"soak.c%d:1|c" % i)
            elif kind == 1:
                pkts.append(b"soak.g%d:2.5|g" % i)
            elif kind == 2:
                pkts.append(b"soak.t%d:3:4:5|ms" % i)
            else:
                pkts.append(b"soak.s%d:u%d|s" % (i, i))
        try:
            server.handle_packet_batch(pkts)
            server.store.apply_all_pending()
            server.flush()  # compile outside the measured window
            times = []
            for _ in range(self.ROUNDS):
                server.handle_packet_batch(pkts)
                server.store.apply_all_pending()
                t0 = time.perf_counter()
                server.flush()
                times.append(time.perf_counter() - t0)
            times.sort()
            return times[len(times) // 2]
        finally:
            server.shutdown()

    def test_ledger_overhead_under_2pct(self):
        off = self._median_flush_s(ledger_on=False)
        on = self._median_flush_s(ledger_on=True)
        # 2% of flush wall time, plus a 200µs absolute epsilon so OS
        # scheduling noise on a tiny flush can't flake the pin
        assert on <= off * 1.02 + 2e-4, (on, off)
