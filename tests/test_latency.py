"""Latency observatory tests (core/latency.py): scalar binning parity,
queue dwell, sample-age watermarks, the flush waterfall acceptance pin
(segments sum within 10% of dispatch_s + device_sync_s), retrace
tagging, the HTTP surface, trace.spans_dropped, and the slow-marked
<2% overhead soak."""

import json
import queue
import time

import numpy as np
import pytest

from veneur_tpu.core import latency as latency_mod
from veneur_tpu.core.latency import (
    InstrumentedQueue, LatencyHist, LatencyObservatory, bin_index_scalar,
    family_segments_sum, waterfall_rounds)
from veneur_tpu.ops import llhist_ref
from veneur_tpu.util import http as vhttp

from test_server import generate_config, setup_server


def drain(server):
    server.store.apply_all_pending()


class TestScalarBinning:
    def test_parity_with_reference_bin_index(self):
        rng = np.random.default_rng(7)
        vals = np.concatenate([
            rng.lognormal(0, 6, 2000),           # spans many decades
            -rng.lognormal(0, 6, 2000),
            rng.uniform(-1e-12, 1e-12, 100),     # zero-bin window
            np.array([0.0, 1e-9, -1e-9, 9.999e15, 1e16, -1e16,
                      np.inf, -np.inf, 1.0, 10.0, 100.0, 0.09999,
                      float("nan")]),
        ])
        ref = llhist_ref.bin_index(vals)
        for v, want in zip(vals.tolist(), ref.tolist()):
            assert bin_index_scalar(v) == want, v

    def test_hist_quantile_error_bound(self):
        hist = LatencyHist("t")
        for v in (0.5, 1.5, 2.5, 120.0):
            hist.observe(v)
        snap = hist.snapshot()
        assert snap["count"] == 4
        assert abs(snap["sum"] - 124.5) < 1e-6
        # max reads the top occupied bin's upper edge: one bin width
        assert 120.0 <= snap["max"] <= 130.0


class TestInstrumentedQueue:
    def test_dwell_measured(self):
        obs = LatencyObservatory()
        q = obs.instrument_queue("q1", maxsize=8)
        assert isinstance(q, InstrumentedQueue)
        q.put("a")
        time.sleep(0.05)
        assert q.get() == "a"
        snap = obs.queue_hist("q1").snapshot()
        assert snap["count"] == 1
        assert snap["p50"] >= 0.04
        # depth gauge reads live occupancy at scrape time
        q.put("b")
        rows = {(name, tuple(tags)): v
                for name, _k, v, tags in obs.telemetry_rows()}
        assert rows[("queue.depth", ("queue:q1",))] == 1.0
        assert rows[("queue.capacity", ("queue:q1",))] == 8.0

    def test_fifo_order_keeps_stamps_aligned(self):
        obs = LatencyObservatory()
        q = obs.instrument_queue("q2", maxsize=0)
        for i in range(100):
            q.put(i)
        for i in range(100):
            assert q.get() == i
        assert obs.queue_hist("q2").snapshot()["count"] == 100

    def test_disabled_observatory_hands_out_plain_queues(self):
        obs = LatencyObservatory(enabled=False)
        q = obs.instrument_queue("q3", maxsize=4)
        assert type(q) is queue.Queue
        obs.note_arrival("dogstatsd")
        assert obs.take_watermarks() == {}
        assert obs.telemetry_rows() == []

    def test_unregister_queue(self):
        obs = LatencyObservatory()
        obs.instrument_queue("gone", maxsize=2)
        obs.unregister_queue("gone")
        assert not any("queue:gone" in (tags[0] if tags else "")
                       for _n, _k, _v, tags in obs.telemetry_rows())


class TestSampleAgeWatermarks:
    def test_watermark_rolls_at_take(self):
        obs = LatencyObservatory()
        t0 = time.time()
        obs.note_arrival("dogstatsd", 3, t=t0 - 5.0)
        obs.note_arrival("dogstatsd", 1, t=t0 - 1.0)
        marks = obs.take_watermarks()
        assert marks["dogstatsd"] == (t0 - 5.0, t0 - 1.0)
        assert obs.take_watermarks() == {}  # rolled

    def test_observe_brackets_oldest_and_newest(self):
        obs = LatencyObservatory()
        t0 = time.time()
        obs.note_arrival("ssf", t=t0 - 50.0)
        obs.note_arrival("ssf", t=t0 - 10.0)
        obs.observe_sample_age(obs.take_watermarks(), t0)
        snap = obs._age_hist("ssf").snapshot()
        assert snap["count"] == 2
        # one observation near 50s, one near 10s, each within one
        # log-linear bin width (10% of the value)
        assert 10.0 <= snap["p50"] <= 11.0 * 1.1
        assert 50.0 <= snap["max"] <= 51.0 * 1.1

    def test_idle_plane_age_series_rolls_and_returns_fresh(self):
        """A plane that idles across AGE_IDLE_SUPPRESS consecutive
        flushes must stop rendering its (stale, otherwise-forever)
        sample-age quantiles; traffic returning recreates the series
        fresh."""
        obs = LatencyObservatory()
        t0 = time.time()
        obs.note_arrival("otlp", t=t0 - 2.0)
        obs.observe_sample_age(obs.take_watermarks(), t0)
        assert any("plane:otlp" in row[3]
                   for row in obs.telemetry_rows() if row[3])
        assert "otlp" in obs.report()["sample_age"]
        # idle flushes: the series survives up to the suppress bound...
        for i in range(LatencyObservatory.AGE_IDLE_SUPPRESS - 1):
            obs.observe_sample_age(obs.take_watermarks(), t0)
            assert "otlp" in obs.report()["sample_age"], i
        # ...then rolls
        obs.observe_sample_age(obs.take_watermarks(), t0)
        assert "otlp" not in obs.report()["sample_age"]
        assert not any("plane:otlp" in row[3]
                       for row in obs.telemetry_rows() if row[3])
        # traffic returns: fresh series, count restarts at the new
        # interval's two observations
        obs.note_arrival("otlp", t=t0 - 1.0)
        obs.observe_sample_age(obs.take_watermarks(), t0)
        snap = obs.report()["sample_age"]["otlp"]
        assert snap["count"] == 2

    def test_active_plane_is_never_rolled(self):
        obs = LatencyObservatory()
        t0 = time.time()
        for _ in range(3 * LatencyObservatory.AGE_IDLE_SUPPRESS):
            obs.note_arrival("dogstatsd", t=t0)
            obs.observe_sample_age(obs.take_watermarks(), t0)
        snap = obs.report()["sample_age"]["dogstatsd"]
        assert snap["count"] == 6 * LatencyObservatory.AGE_IDLE_SUPPRESS


class TestFlushWaterfall:
    """The acceptance pin: per-family×device segments sum to within 10%
    of the recorded dispatch_s + device_sync_s totals."""

    def _flushed_server(self):
        server, observer = setup_server()
        for pkt in (b"wf.c:1|c", b"wf.g:2|g", b"wf.t:3|ms", b"wf.s:x|s",
                    b"wf.l:4|l"):
            server.handle_metric_packet(pkt)
        drain(server)
        server.flush()  # cold flush compiles; measure the warm one
        for pkt in (b"wf.c:1|c", b"wf.g:2|g", b"wf.t:3|ms", b"wf.s:y|s",
                    b"wf.l:4|l"):
            server.handle_metric_packet(pkt)
        drain(server)
        server.flush()
        return server, observer

    def test_segments_sum_within_10pct_of_phase_totals(self):
        server, _observer = self._flushed_server()
        try:
            rounds = server.telemetry.flushes.snapshot()
            r = rounds[-1]
            fams = r["families"]
            assert set(fams) == {"counter", "gauge", "histogram", "llhist",
                                 "set", "status"}
            total = r["phases"]["dispatch_s"] + r["phases"]["device_sync_s"]
            seg_sum = family_segments_sum(fams)
            assert total > 0
            assert abs(seg_sum - total) <= 0.10 * total, (seg_sum, total)
            # device families carry at least one per-device sync segment
            for fam in ("counter", "gauge", "histogram", "llhist"):
                assert fams[fam]["devices"], fam
        finally:
            server.shutdown()

    def test_waterfall_view_shape(self):
        server, _observer = self._flushed_server()
        try:
            rounds = waterfall_rounds(server.telemetry.flushes.snapshot())
            tree = rounds[-1]
            assert tree["families"]
            assert tree["segments_sum_s"] <= tree["device_total_s"] * 1.10
            assert tree["segments_sum_s"] >= tree["device_total_s"] * 0.90
            assert "sinks" in tree and "phases" in tree
        finally:
            server.shutdown()

    def test_family_child_spans_under_flush_span(self):
        server, _observer = self._flushed_server()
        try:
            server.start()
            # the flush span loops through the internal trace client into
            # this server's own span pipeline; flush once more with the
            # pipeline live so the family child spans land
            server.handle_metric_packet(b"wf.c:1|c")
            drain(server)
            server.flush()
            server.trace_client.flush(timeout=2.0)
            ext = server.metric_extraction
            deadline = time.time() + 2.0
            seen = 0
            while time.time() < deadline:
                seen = ext.spans_processed
                if seen:
                    break
                time.sleep(0.05)
            assert seen > 0  # flush + flush.family/flush.sink children
        finally:
            server.shutdown()

    def test_retrace_tagged_after_capacity_resize(self):
        server, _observer = setup_server()
        try:
            server.handle_metric_packet(b"rt.seed:1|c")
            drain(server)
            server.flush()  # warm
            # blow past counter_capacity (128) to force a doubling; the
            # first post-resize apply is the jit retrace (PR-4 hook)
            for i in range(200):
                server.handle_metric_packet(b"rt.k%d:1|c" % i)
            drain(server)
            server.flush()
            fams = server.telemetry.flushes.snapshot()[-1]["families"]
            assert fams["counter"].get("retrace") is True
            assert fams["counter"]["recompile_s"] > 0
        finally:
            server.shutdown()


class TestSampleAgeAcceptance:
    """An injected known-age sample is reflected in the plane's
    pipeline.sample_age llhist within one bin width."""

    def test_injected_age_lands_within_one_bin(self):
        server, _observer = setup_server()
        try:
            server.handle_metric_packet(b"age.warm:1|c")
            drain(server)
            server.flush()  # warm: the measured flush stays fast
            t_inject = time.time()
            # a batch that arrived 100s ago (bin [100, 110): width 10)
            server.latency.note_arrival("dogstatsd", 1, t=t_inject - 100.0)
            server.handle_metric_packet(b"age.now:1|c")
            drain(server)
            server.flush()
            elapsed = time.time() - t_inject
            snap = server.latency._age_hist("dogstatsd").snapshot()
            assert snap["count"] >= 2
            # true age at ack is 100..100+elapsed; the llhist may round
            # up by at most one bin width of the landing bin (<=10% of
            # the value)
            assert snap["max"] >= 100.0
            assert snap["max"] <= (100.0 + elapsed) * 1.10
        finally:
            server.shutdown()

    def test_each_plane_stamped_at_ingest(self):
        server, _observer = setup_server()
        try:
            server.handle_packet_batch([b"pl.c:1|c"])
            from veneur_tpu import ssf
            span = ssf.SSFSpan(id=1, trace_id=1, name="op", service="t",
                               start_timestamp=1, end_timestamp=2)
            server.handle_ssf_packet(span.SerializeToString())
            marks = server.latency.take_watermarks()
            assert "dogstatsd" in marks and "ssf" in marks
        finally:
            server.shutdown()

    def test_forward_plane_stamped_by_import_server(self):
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.forward.server import ImportServer
        global_server, observer = setup_server(forward_address="")
        imp = ImportServer(global_server, "127.0.0.1:0")
        imp.start()
        local, _lo = setup_server(forward_address=imp.address)
        client = ForwardClient(imp.address, deadline=10.0)
        try:
            local.handle_metric_packet(b"fwd.age:7|ms")
            drain(local)
            from veneur_tpu.core.flusher import flush_columnstore_batch
            _batch, fwd = flush_columnstore_batch(
                local.store, True, local.percentiles, local.aggregates)
            assert client.forward(fwd) > 0
            marks = global_server.latency.take_watermarks()
            assert "forward" in marks
        finally:
            client.close()
            imp.stop()
            local.shutdown()
            global_server.shutdown()


class TestHTTPSurface:
    def _api_url(self, api, path):
        host, port = api.address
        return f"http://{host}:{port}{path}"

    def test_debug_latency_and_waterfall_endpoints(self):
        cfg = generate_config(http_address="127.0.0.1:0")
        server, _observer = setup_server(cfg)
        try:
            server.start()
            server.handle_metric_packet(b"ep.c:1|c")
            drain(server)
            server.flush()
            api = server.http_api
            status, body = vhttp.get(self._api_url(api, "/debug/latency"))
            assert status == 200
            rep = json.loads(body)
            assert rep["enabled"] is True
            assert "span_channel" in rep["queues"]
            assert "trace_client" in rep["queues"]
            status, body = vhttp.get(
                self._api_url(api, "/debug/flush?waterfall=1&n=4"))
            assert status == 200
            rounds = json.loads(body)["rounds"]
            assert rounds
            last = rounds[-1]
            assert last["families"]
            assert last["segments_sum_s"] == pytest.approx(
                last["device_total_s"], rel=0.10)
            # waterfall=0 is OFF: the plain flush listing comes back
            status, body = vhttp.get(
                self._api_url(api, "/debug/flush?waterfall=0"))
            assert status == 200
            assert "rounds" in json.loads(body)
            assert "capacity" in json.loads(body)  # flushes_json shape
        finally:
            server.shutdown()

    def test_metrics_rows_exported(self):
        server, _observer = setup_server()
        try:
            server.latency.note_arrival("dogstatsd", 1,
                                        t=time.time() - 2.0)
            server.handle_metric_packet(b"mr.c:1|c")
            drain(server)
            server.flush()
            text = server.telemetry.registry.render_prometheus()
            for want in ("veneur_pipeline_sample_age_p50",
                         "veneur_pipeline_sample_age_count_total",
                         "veneur_queue_depth", "veneur_queue_capacity",
                         "veneur_queue_dwell_p99",
                         'plane="dogstatsd"', 'queue="span_channel"'):
                assert want in text, want
        finally:
            server.shutdown()

    def test_observatory_disabled_via_config(self):
        server, _observer = setup_server(latency_observatory=False)
        try:
            assert type(server.span_chan) is queue.Queue
            server.handle_metric_packet(b"off.c:1|c")
            drain(server)
            server.flush()
            r = server.telemetry.flushes.snapshot()[-1]
            assert "families" not in r
            text = server.telemetry.registry.render_prometheus()
            assert "veneur_queue_depth" not in text
        finally:
            server.shutdown()


class TestTraceDropExport:
    def test_trace_spans_dropped_in_metrics(self, caplog):
        server, _observer = setup_server()
        try:
            # choke the trace client's bounded buffer (sender thread is
            # live, so drive hard past capacity)
            import logging
            with caplog.at_level(logging.WARNING, "veneur_tpu.trace"):
                server.trace_client.close()  # closed client counts drops
                server.trace_client.record(None)
            assert server.trace_client.spans_dropped >= 1
            text = server.telemetry.registry.render_prometheus()
            assert "veneur_trace_spans_dropped_total" in text
            assert any("trace client dropped its first span" in r.message
                       for r in caplog.records)
        finally:
            server.shutdown()

    def test_buffered_backend_drop_counted(self):
        from veneur_tpu import trace as trace_mod

        class Boom:
            def send(self, span):
                raise RuntimeError("down")

            def flush(self):
                pass

            def close(self):
                pass

        client = trace_mod.Client(trace_mod.BufferedBackend(Boom(),
                                                            capacity=4))
        try:
            span = client.start_span("x", service="t")
            span.finish()
            client.flush(timeout=2.0)
            assert client.spans_dropped >= 1
        finally:
            client.close()


@pytest.mark.slow
class TestOverheadSoak:
    """Observatory cost pinned under 2% of flush wall time vs
    latency_observatory: false (the acceptance guard)."""

    N_KEYS = 1500
    ROUNDS = 30

    def _median_flush_s(self, observatory_on: bool) -> float:
        cfg = generate_config(latency_observatory=observatory_on)
        cfg.tpu.counter_capacity = 4096
        cfg.tpu.gauge_capacity = 4096
        cfg.tpu.histo_capacity = 4096
        cfg.tpu.set_capacity = 1024
        server, _observer = setup_server(cfg)
        pkts = []
        for i in range(self.N_KEYS):
            kind = i % 4
            if kind == 0:
                pkts.append(b"soak.c%d:1|c" % i)
            elif kind == 1:
                pkts.append(b"soak.g%d:2.5|g" % i)
            elif kind == 2:
                pkts.append(b"soak.t%d:3:4:5|ms" % i)
            else:
                pkts.append(b"soak.s%d:u%d|s" % (i, i))
        try:
            server.handle_packet_batch(pkts)
            drain(server)
            server.flush()  # compile outside the measured window
            times = []
            for _ in range(self.ROUNDS):
                server.handle_packet_batch(pkts)
                drain(server)
                t0 = time.perf_counter()
                server.flush()
                times.append(time.perf_counter() - t0)
            times.sort()
            return times[len(times) // 2]
        finally:
            server.shutdown()

    def test_observatory_overhead_under_2pct(self):
        off = self._median_flush_s(observatory_on=False)
        on = self._median_flush_s(observatory_on=True)
        # 2% of flush wall time, plus a 200µs absolute epsilon so OS
        # scheduling noise on a tiny flush can't flake the pin
        assert on <= off * 1.02 + 2e-4, (on, off)
