"""Adversarial fuzz of the hand-rolled native wire parsers.

vnt_import_count / vnt_import_parse / vnt_route_parse / vnt_ssf_parse
read bytes straight off the network in C++; a crash there takes the
whole server down, so beyond the structural tests they get hammered
with mutated-valid and pure-random buffers. The contract under fuzz:
never crash, never hang, and either parse cleanly or reject (the
Python wrappers return None); anything the native path accepts must
not disagree with upb about metric COUNT."""

from __future__ import annotations

import numpy as np
import os

import pytest

from veneur_tpu import native
from veneur_tpu.forward.protos import forward_pb2, metric_pb2, tdigest_pb2
from veneur_tpu.forward.wire import _frame_v1
from veneur_tpu.ops import batch_tdigest

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

# FUZZ_ROUNDS=20000 (etc.) runs an extended soak; default keeps CI fast
ROUNDS = int(os.environ.get("FUZZ_ROUNDS", "400"))


def valid_body(rng) -> bytes:
    metrics = []
    for i in range(int(rng.integers(1, 6))):
        kind = int(rng.integers(0, 4))
        m = metric_pb2.Metric(name=f"fz.{i}", tags=[f"t:{i}"],
                              scope=metric_pb2.Global)
        if kind == 0:
            m.type = metric_pb2.Counter
            m.counter.value = int(rng.integers(-1000, 1000))
        elif kind == 1:
            m.type = metric_pb2.Gauge
            m.gauge.value = float(rng.standard_normal())
        elif kind == 2:
            m.type = metric_pb2.Timer
            d = tdigest_pb2.MergingDigestData(
                compression=batch_tdigest.COMPRESSION, min=0, max=9)
            for _ in range(int(rng.integers(1, 8))):
                d.main_centroids.add(mean=float(rng.standard_normal()),
                                     weight=float(rng.random() + 0.1))
            m.histogram.t_digest.CopyFrom(d)
        else:
            m.type = metric_pb2.Set
            m.set.hyper_log_log = bytes(rng.integers(
                0, 256, int(rng.integers(0, 40)), dtype=np.uint8))
        metrics.append(m)
    return b"".join(_frame_v1(m.SerializeToString()) for m in metrics)


def mutate(body: bytes, rng) -> bytes:
    b = bytearray(body)
    op = int(rng.integers(0, 4))
    if op == 0 and b:  # flip random bytes
        for _ in range(int(rng.integers(1, 8))):
            b[int(rng.integers(0, len(b)))] = int(rng.integers(0, 256))
    elif op == 1 and b:  # truncate
        del b[int(rng.integers(0, len(b))):]
    elif op == 2:  # splice random garbage
        pos = int(rng.integers(0, len(b) + 1))
        b[pos:pos] = bytes(rng.integers(0, 256, int(rng.integers(1, 32)),
                                        dtype=np.uint8))
    else:  # duplicate a slice
        if b:
            s = int(rng.integers(0, len(b)))
            e = min(len(b), s + int(rng.integers(1, 64)))
            b.extend(b[s:e])
    return bytes(b)


def upb_count(body: bytes):
    try:
        return len(forward_pb2.MetricList.FromString(body).metrics)
    except Exception:
        return None


class TestImportParserFuzz:
    def test_mutated_bodies_never_crash(self):
        rng = np.random.default_rng(1234)
        for _ in range(ROUNDS):
            body = mutate(valid_body(rng), rng)
            out = native.parse_metric_list(
                body, batch_tdigest.C, batch_tdigest.COMPRESSION)
            if out is not None:
                # whatever the native path accepts, upb must agree the
                # wire STRUCTURE is sound and the count matches
                want = upb_count(body)
                # proto3 allows last-field-wins / unknown fields that
                # upb also accepts; only compare when upb parses
                if want is not None:
                    assert out.consumed == want

    def test_pure_random_never_crashes(self):
        rng = np.random.default_rng(99)
        for _ in range(ROUNDS):
            blob = bytes(rng.integers(0, 256, int(rng.integers(0, 512)),
                                      dtype=np.uint8))
            native.parse_metric_list(blob, batch_tdigest.C,
                                     batch_tdigest.COMPRESSION)
            native.route_parse(blob)

    def test_route_parse_agrees_with_import_on_validity(self):
        rng = np.random.default_rng(7)
        for _ in range(ROUNDS):
            body = mutate(valid_body(rng), rng)
            imp = native.parse_metric_list(
                body, batch_tdigest.C, batch_tdigest.COMPRESSION)
            rt = native.route_parse(body)
            # both walk the same frame structure: accept/reject together
            assert (imp is None) == (rt is None), body.hex()

    def test_structure_accepted_implies_upb_structure(self):
        """The native parser must never accept a buffer whose FRAME
        structure upb rejects (it may be stricter about nested values,
        never looser about framing)."""
        rng = np.random.default_rng(42)
        looser = 0
        for _ in range(ROUNDS):
            body = mutate(valid_body(rng), rng)
            out = native.parse_metric_list(
                body, batch_tdigest.C, batch_tdigest.COMPRESSION)
            if out is not None and upb_count(body) is None:
                looser += 1
        # upb additionally validates utf-8 in string fields, which the
        # native walk defers to the stub/dispatch layer — allow a small
        # residue but no systematic laxness
        assert looser <= ROUNDS * 0.1, looser


class TestSsfDecoderFuzz:
    def test_ssf_buffer_never_crashes(self):
        from veneur_tpu import ssf
        from veneur_tpu.config import Config
        from veneur_tpu.core.server import Server
        from veneur_tpu.sinks.channel import ChannelMetricSink

        cfg = Config()
        cfg.interval = 3600
        cfg.statsd_listen_addresses = []
        cfg.apply_defaults()
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        rng = np.random.default_rng(5)
        sp = ssf.SSFSpan(id=1, trace_id=2, name="f", service="s",
                         start_timestamp=1, end_timestamp=2)
        sp.metrics.append(ssf.count("c", 1))
        base = sp.SerializeToString()
        for _ in range(ROUNDS):
            # the production packet-batch entry point (it builds the
            # joined/offs/lens buffer the native decoder consumes)
            server.handle_ssf_batch([mutate(base, rng) for _ in range(3)])
        server.flush()  # whatever was accepted must still flush cleanly
