"""Batch ingest pipeline tests (the `ingest` marker): llhist wire-type
parity on a fuzz corpus (native C++ and numpy fallback vs the scalar
parser), batch-granular admission/shedding with exact per-class counts
under a strict flow ledger, SPSC ring backpressure (a full ring blocks
the reader — no silent drop), supervisor coverage of a wedged pump
dispatcher, kernel-drop inode watching after the listener rebuild, and
the ingest_ring observability surface.
"""

from __future__ import annotations

import os
import socket
import time

import numpy as np
import pytest

from veneur_tpu import native
from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink

pytestmark = pytest.mark.ingest

needs_native = pytest.mark.skipif(
    not native.available(),
    reason=f"native parser unavailable: {native.unavailable_reason()}")


def make_server(disable_native: bool = False, **overrides):
    cfg = Config()
    cfg.interval = 3600.0
    cfg.tpu.disable_native_parser = disable_native
    for key, value in overrides.items():
        setattr(cfg, key, value)
    cfg.apply_defaults()
    ch = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[ch]), ch


def llhist_state(server) -> np.ndarray:
    server.store.llhists.apply_pending()
    return np.asarray(server.store.llhists.state)


# ---------------------------------------------------------------------------
# llhist wire type in the batch decoders


def _llhist_fuzz_corpus():
    """Multi-value `l` lines spanning the whole bin window plus both
    clamp edges, bin-boundary magnitudes, negatives, rates, and junk —
    the corpus that pins the C++ binning against llhist_ref."""
    rng = np.random.default_rng(1234)
    lines = []
    # random magnitudes across (and beyond) the representable window
    mags = 10.0 ** rng.uniform(-12, 18, 120)
    signs = rng.choice([-1.0, 1.0], 120)
    vals = mags * signs
    for i in range(0, 120, 4):
        chunk = b":".join(b"%r" % v for v in vals[i:i + 4])
        lines.append(b"fz.%d:%s|l" % (i % 7, chunk))
    # exact bin edges: m * 10^(e-1) and the window/clamp boundaries
    edges = [1e-9, 9.9e-9, 1e16, 9.9e15, 1.0, 10.0, 99.0, 0.0, -0.0,
             1e-10, -1e17, 5.5, -5.5, 2.5e-5, 12.0, 12.0000001]
    for i, v in enumerate(edges):
        lines.append(b"edge.%d:%r|l" % (i % 3, v))
    # rates (integral and rounding-edge weights) + multi-value
    lines.append(b"rated:3.7:42|l|@0.5")
    lines.append(b"rated2:3.7|l|@0.4")    # 1/0.4 = 2.5 -> banker's 2
    lines.append(b"rated3:1000|l|@0.125")
    # absurd-but-valid rate: 1/1e-10 saturates at INT32_MAX in every
    # decoder (scalar, numpy, C++) instead of wrapping/raising
    lines.append(b"rated4:7|l|@0.0000000001")
    # slow-path material: junk values, NaN/Inf, unknown-but-llhist
    lines.append(b"fz.0:nan|l")
    lines.append(b"fz.0:inf|l")
    lines.append(b"fz.0:1_0|l")
    lines.append(b"fz.0:|l")
    lines.append(b"fz.0:1:|l")
    lines.append(b"fz.0::1|l")
    return lines


class TestLLHistWireType:
    def _run_batch(self, disable_native: bool):
        """Corpus through the batch path (native or numpy columnar):
        pass 1 interns via the slow path, passes 2-3 ride the columns."""
        server, ch = make_server(disable_native)
        try:
            lines = _llhist_fuzz_corpus()
            for _ in range(3):
                server.handle_packet_batch(lines)
            ing = server._ingester or server._py_ingester
            assert ing.interned_keys > 0  # fast path actually engaged
            return (llhist_state(server).copy(),
                    server.store.llhists.samples_total,
                    server.store.llhists.clamped_total,
                    dict(server.stats))
        finally:
            server.shutdown()

    def _run_scalar(self):
        """Same corpus through the per-packet scalar parser path."""
        server, ch = make_server(disable_native=True)
        try:
            lines = _llhist_fuzz_corpus()
            for _ in range(3):
                for line in lines:
                    server.handle_packet_buffer(line)
            return (llhist_state(server).copy(),
                    server.store.llhists.samples_total,
                    server.store.llhists.clamped_total,
                    dict(server.stats))
        finally:
            server.shutdown()

    @needs_native
    def test_native_binning_matches_scalar_parser(self):
        state_n, samples_n, clamped_n, stats_n = self._run_batch(False)
        state_s, samples_s, clamped_s, stats_s = self._run_scalar()
        assert np.array_equal(state_n, state_s)  # registers bit-identical
        assert samples_n == samples_s
        assert clamped_n == clamped_s
        assert stats_n["parse_errors"] == stats_s["parse_errors"]

    def test_numpy_fallback_matches_scalar_parser(self):
        state_p, samples_p, clamped_p, stats_p = self._run_batch(True)
        state_s, samples_s, clamped_s, stats_s = self._run_scalar()
        assert np.array_equal(state_p, state_s)
        assert samples_p == samples_s
        assert clamped_p == clamped_s
        assert stats_p["parse_errors"] == stats_s["parse_errors"]

    @needs_native
    def test_native_and_fallback_agree(self):
        state_n, samples_n, clamped_n, _ = self._run_batch(False)
        state_p, samples_p, clamped_p, _ = self._run_batch(True)
        assert np.array_equal(state_n, state_p)
        assert (samples_n, clamped_n) == (samples_p, clamped_p)


# ---------------------------------------------------------------------------
# numpy columnar fallback: full-grammar parity with the scalar path


FULL_CORPUS = [
    b"c1:5|c|#a:b", b"c1:2|c|@0.5|#a:b", b"g1:2.5|g", b"g1:7|g",
    b"t1:1:2:3:4|ms|@0.5|#x:y", b"h1:0.25|h", b"d1:9|d",
    b"s1:u1|s\ns1:u2|s\ns1:u1|s", b"ll1:5:50:500|l",
    b"bad packet", b"nopipe:1", b"novalue|c", b":1|c",
    b"x:|c", b"x:1:|c", b"x::1|c",
    b"weird:1e999|c", b"tiny:1e-999|g", b"neg:-12.5|g", b"plus:+3|c",
    b"exp:2.5e2|ms", b"dot:.5|g", b"dotted:5.|g",
    b"under:1_0|c", b"space: 1|c", b"nan:nan|g", b"inf:inf|g",
    b"hex:0x10|c", b"_sc|check|9", b"_e{2,2}:ab|cd|t:error",
    b"setnonascii:caf\xc3\xa9|s", b"s1:\xff\xfe|s",
    b"multi:1:2:3|c|#m:n", b"glob:1|c|#veneurglobalonly",
]


class TestNumpyFallbackParity:
    def test_corpus_matches_scalar_path(self):
        """The numpy columnar decoder must be observably identical to
        the per-packet scalar path across the whole grammar."""
        outs = []
        for batched in (True, False):
            server, ch = make_server(disable_native=True)
            try:
                for _ in range(2):
                    if batched:
                        server.handle_packet_batch(FULL_CORPUS)
                    else:
                        for dgram in FULL_CORPUS:
                            server.handle_packet_buffer(dgram)
                server.flush()
                rows = sorted(
                    (m.name, m.type.name, round(float(m.value), 4),
                     tuple(m.tags))
                    for m in ch.wait_flush())
                stats = dict(server.stats)
                stats.pop("batches_dispatched")  # batch-path only
                outs.append((rows, stats))
            finally:
                server.shutdown()
        assert outs[0][0] == outs[1][0]
        assert outs[0][1] == outs[1][1]

    def test_decoder_interns_after_slow_path(self):
        server, _ch = make_server(disable_native=True)
        try:
            assert server._py_ingester is not None
            server.handle_packet_batch([b"pyk:1|c", b"pyl:2|l"])
            assert server._py_ingester.interned_keys >= 2
            # second pass rides the columns: no new slow-path registers
            before = dict(server._py_ingester.decoder.table)
            server.handle_packet_batch([b"pyk:1|c", b"pyl:2|l"])
            assert dict(server._py_ingester.decoder.table) == before
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# batch-granular admission + exact per-class shed accounting


class TestBatchShedLedger:
    def test_shed_books_exact_sample_counts_at_30pct(self):
        """The 30%-shed ledger drill: 3 of 10 batches rejected; the
        shed table must book the exact per-class sample counts from the
        batches' type-code columns, and the strict flow ledger must
        close the interval with zero unexplained imbalance."""
        server, _ch = make_server(disable_native=False,
                                  ledger_strict=True)
        try:
            ing = server._ingester or server._py_ingester
            # each batch: 4 counter + 1 gauge + 3 histo + 2 llhist + 1 set
            batch = b"\n".join([
                b"bc:1:2:3:4|c", b"bg:7|g", b"bh:1:2:3|ms",
                b"bl:5:50|l", b"bs:member|s"])
            ing.ingest_buffer(batch)  # intern pass (slow path, admitted)
            for i in range(10):
                ing.ingest_buffer(batch, shed_nonessential=(i < 3))
            shed = server.overload.shed_snapshot()
            # histo(3) + llhist(2) per rejected batch; set(1) each
            assert shed.get("histogram|rate_limit") == 3 * (3 + 2)
            assert shed.get("set|rate_limit") == 3 * 1
            # flush closes the ledger interval; strict mode raises on
            # any conservation imbalance
            server.flush()
            assert server.ledger.history_imbalances()[-1]["ingest"] == 0.0
        finally:
            server.shutdown()

    def test_over_limit_batches_keep_counters_end_to_end(self):
        """Token-bucket batch admission end to end: counter deltas from
        over-limit batches still land; histogram/llhist columns shed."""
        server, ch = make_server(disable_native=False,
                                 ingest_rate_limit_statsd=1.0,
                                 ingest_rate_limit_burst=1.0)
        try:
            for _ in range(4):
                server.handle_packet_batch([b"ol.c:1|c\nol.l:5|l"])
            server.flush()
            got = {m.name: m for m in ch.wait_flush()}
            assert got["ol.c"].value == 4.0  # every delta kept
            shed = server.overload.shed_snapshot()
            assert shed.get("histogram|rate_limit", 0) >= 1
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# SPSC ring backpressure & crash coverage


@needs_native
class TestRingBackpressure:
    def test_full_ring_blocks_reader_no_silent_drop(self):
        """With no dispatcher draining, the reader fills its ring and
        BLOCKS (counted stalls); once draining starts, every line the
        readers accepted is accounted — nothing vanishes in-process."""
        eng = native.Engine()
        eng.register(b"rb|c", native.FAM_COUNTER, 0, 1.0)
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 << 20)
        recv.bind(("127.0.0.1", 0))
        addr = recv.getsockname()
        send = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        pump = native.Pump(eng, [recv.fileno()], max_dgram=2048,
                           max_len=2047, chunk_cap=512, ring_slots=3,
                           seal_age_ms=20)
        try:
            dgram = b"\n".join([b"rb:1|c"] * 100)
            n_dgrams = 60  # 6000 samples >> 3 rings * 512 samples
            for _ in range(n_dgrams):
                send.sendto(dgram, addr)
            deadline = time.time() + 5.0
            while time.time() < deadline and pump.stalls() == 0:
                time.sleep(0.05)
            assert pump.stalls() > 0  # ring filled; reader blocked
            depths, caps, sealed, stalls = pump.ring_stats()
            assert depths[0] == caps[0]  # ready ring is full
            assert sealed[0] >= caps[0]
            assert stalls[0] > 0
            # now drain: every accepted line must surface in a chunk
            got = 0
            idle = 0
            while got < n_dgrams * 100 and idle < 40:
                chunk = pump.next(100)
                if chunk is None:
                    idle += 1
                    continue
                idle = 0
                got += chunk.samples + len(chunk.unknown)
                pump.release(chunk)
            assert got == n_dgrams * 100
        finally:
            pump.stop()
            pump.close()
            recv.close()
            send.close()

    def test_dead_dispatcher_caught_by_supervisor(self):
        """A wedged pump dispatcher stops heartbeating; the PR-3
        supervisor flags the ingest-pump component."""
        from veneur_tpu.core.ingest import BatchIngester
        server, _ch = make_server(supervisor_deadline=0.4,
                                  statsd_listen_addresses=[
                                      "udp://127.0.0.1:0"])
        try:
            server.start()
            sup = server.overload.supervisor
            comps = [c for c in sup._beats if c.startswith("ingest-pump:")]
            assert comps  # dispatcher registered itself
            orig = BatchIngester._dispatch_one
            # wedge: the dispatcher loop re-resolves the method each
            # iteration, so the class patch takes effect immediately;
            # one call outlasts the deadline, so the next beat is late
            BatchIngester._dispatch_one = (
                lambda self, *a, **k: time.sleep(1.0) or False)
            try:
                deadline = time.time() + 5.0
                flagged = []
                while time.time() < deadline and not flagged:
                    time.sleep(0.2)
                    flagged = [c for c in sup.check()
                               if c.startswith("ingest-pump:")]
                    flagged += [c for c in sup.stalled_components()
                                if c.startswith("ingest-pump:")]
                assert flagged
            finally:
                BatchIngester._dispatch_one = orig
        finally:
            server.shutdown()

    def test_kernel_drop_monitor_watches_listener_inodes(self):
        """After the ring rebuild the kernel-drop monitor must still
        poll the pump's actual socket inodes (/proc/net/udp rows)."""
        server, _ch = make_server(
            statsd_listen_addresses=["udp://127.0.0.1:0"], num_readers=2)
        try:
            server.start()
            listener = server._listeners[0]
            want = {os.fstat(s.fileno()).st_ino for s in listener._socks}
            with server.overload.kernel_drops._lock:
                watched = set(server.overload.kernel_drops._watched)
            assert want <= watched
            server.overload.kernel_drops.poll()  # must not raise
        finally:
            server.shutdown()


@needs_native
class TestSwapIngestRace:
    """PR-15 generation-swap pin: the overlapped flush swaps a table's
    pending columns + device generation at the interval boundary while
    ingest threads keep hammering add_batch. A swap must never drop a
    pending chunk (every sample lands in exactly one interval) and the
    strict-ledger ingest identity must stay clean through the overlap."""

    def test_counter_swap_add_batch_hammer_conserves_every_sample(self):
        import threading

        from veneur_tpu.core.columnstore import CounterTable
        from veneur_tpu.samplers.parser import Parser

        table = CounterTable(capacity=256, batch_cap=64)
        table.family = "counter"
        n_keys = 32
        parser = Parser()
        for i in range(n_keys):  # intern the rows once, slow path
            parser.parse_metric_fast(b"hammer.%d:0|c" % i, table.add)
        table.apply_pending()
        table.snapshot_and_reset()  # discard the zero-sample warmup

        writers = 4
        rounds = 200
        wrote = [0] * writers

        def writer(w):
            rows = np.arange(n_keys, dtype=np.int32)
            vals = np.ones(n_keys, np.float32)
            rates = np.ones(n_keys, np.float32)
            for _ in range(rounds):
                table.add_batch(rows, vals, rates)
                wrote[w] += n_keys

        threads = [threading.Thread(target=writer, args=(w,))
                   for w in range(writers)]
        for t in threads:
            t.start()
        # hammer generation swaps (the overlapped flush's critical-path
        # half + background readout) against the live writers
        total_seen = 0.0
        while any(t.is_alive() for t in threads):
            snap = table.readout(table.swap_out())
            vals, _touched, _meta = table.snapshot_finish(snap)
            table.recycle(snap)
            total_seen += float(vals[:n_keys].sum())
        for t in threads:
            t.join()
        # final interval drains whatever the last swap raced past
        table.apply_pending()
        vals, _t, _m = table.snapshot_and_reset()
        total_seen += float(vals[:n_keys].sum())
        assert total_seen == float(sum(wrote))

    def test_server_flush_hammer_strict_ledger_clean(self):
        """Whole-pipeline hammer under flush_async + ledger_strict:
        python-path ingest races overlapped flushes; counters conserve
        exactly across every delivered interval and no flush raises a
        conservation imbalance."""
        import threading

        server, ch = make_server(flush_async=True, ledger_strict=True)
        try:
            writers = 3
            per_writer = 400
            keys = 16

            def writer(w):
                for i in range(per_writer):
                    server.handle_metric_packet(
                        b"flood.%d:1|c" % (i % keys))

            threads = [threading.Thread(target=writer, args=(w,))
                       for w in range(writers)]
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                server.flush()  # strict ledger: raises on any leak
                time.sleep(0.01)
            for t in threads:
                t.join()
            server.store.apply_all_pending()
            server.flush()  # swap the tail interval
            server.flush()  # deliver it (pipeline depth 1)
            server.flush()  # and the (empty) one after
            total = sum(m.value for m in ch.drain()
                        if m.name.startswith("flood."))
            assert total == float(writers * per_writer)
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()


class TestRingObservability:
    def test_ring_rows_and_latency_queues(self):
        server, _ch = make_server(
            statsd_listen_addresses=["udp://127.0.0.1:0"], num_readers=2)
        try:
            server.start()
            send = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            addr = server.local_addr("udp")
            for _ in range(3):
                send.sendto(b"ring.obs:1|c", addr)
            deadline = time.time() + 5.0
            while time.time() < deadline and server.store.processed < 1:
                time.sleep(0.05)
            send.close()
            rows = {name for name, _k, _v, _t
                    in server._ring_telemetry_rows()}
            assert rows == {"ingest.ring.depth", "ingest.ring.capacity",
                            "ingest.ring.sealed_total",
                            "ingest.ring.stalls_total"}
            report = server.latency.report()
            ring_queues = [q for q in report["queues"]
                           if q.startswith("ingest_ring:")]
            assert len(ring_queues) == 2  # one per reader
            # dwell llhist observed at least one sealed chunk
            assert any(
                report["queues"][q].get("dwell", {}).get("count", 0) > 0
                for q in ring_queues)
        finally:
            server.shutdown()
