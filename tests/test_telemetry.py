"""Pull-side self-telemetry tests: the internal registry, Prometheus
exposition, the flight recorder (/debug/events), the flush-round table
(/debug/flush), per-sink flush-outcome recording, and the metric-name
inventory lint (scripts/check_metric_names.py)."""

import json
import pathlib
import re
import sys
import threading
import time

import pytest

from veneur_tpu.core import telemetry
from veneur_tpu.core.telemetry import (
    HISTOGRAM_BOUNDS, EventRecorder, FlushRecorder, Registry, Telemetry,
    prom_labels, prom_name,
)
from veneur_tpu.sinks import MetricSink
from veneur_tpu.util import http as vhttp
from veneur_tpu.util.scopedstatsd import NullClient, ScopedClient

from test_server import generate_config, setup_server

# every exposition line is a comment or name{labels} value
_EXPO_LINE = re.compile(
    r"^(# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* ?.*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9].*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [+-]?(Inf|NaN).*)$")


def assert_valid_exposition(text: str) -> None:
    assert text.endswith("\n")
    for line in text.rstrip("\n").split("\n"):
        assert _EXPO_LINE.match(line), f"bad exposition line: {line!r}"


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = Registry()
        reg.count("hits", 2)
        reg.count("hits", 3)
        reg.gauge("level", 1.0)
        reg.gauge("level", 7.5)  # last write wins
        reg.observe("latency", 0.003)
        snap = reg.snapshot()
        assert snap["counters"]["hits"] == 5
        assert snap["gauges"]["level"] == 7.5
        assert snap["histograms"]["latency"] == 1

    def test_statsd_tee_semantics(self):
        reg = Registry()
        reg.record_statsd("c", 1, "c", [], 0.1)   # sampled: scaled 1/rate
        reg.record_statsd("g", 4.2, "g", ["a:b"], 1.0)
        reg.record_statsd("t", 250.0, "ms", [], 1.0)  # ms in, seconds kept
        snap = reg.snapshot()
        assert snap["counters"]["c"] == pytest.approx(10.0)
        assert snap["gauges"]["g|a:b"] == 4.2
        rendered = reg.render_prometheus()
        # 250ms lands in the (0.2, 0.5] bucket: cumulative count at
        # le=0.5 is 1 while le=0.2 is still 0
        assert 'veneur_t_bucket{le="0.2"} 0' in rendered
        assert 'veneur_t_bucket{le="0.5"} 1' in rendered
        assert "veneur_t_sum 0.25" in rendered
        assert "veneur_t_count 1" in rendered

    def test_series_cap_bounds_memory(self):
        reg = Registry(max_series=10)
        for i in range(1000):
            reg.count(f"metric.{i}")
        snap = reg.snapshot()
        assert len(snap["counters"]) == 10
        assert snap["series_dropped"] == 990
        # existing series still update at the cap
        reg.count("metric.0", 5)
        assert reg.snapshot()["counters"]["metric.0"] == 6
        assert "veneur_telemetry_series_dropped 990" in \
            reg.render_prometheus()

    def test_histogram_bins_are_fixed(self):
        reg = Registry()
        for i in range(10_000):
            reg.observe("lat", (i % 700) * 0.01)
        (key, hist), = reg._histograms.items()
        assert len(hist.buckets) == len(HISTOGRAM_BOUNDS) + 1
        assert hist.count == 10_000

    def test_collectors_render_fresh(self):
        reg = Registry()
        live = {"n": 0}
        reg.add_collector(lambda: [("live.counter", "counter",
                                    float(live["n"]), ())])
        live["n"] = 3
        assert "veneur_live_counter_total 3" in reg.render_prometheus()
        live["n"] = 8
        assert "veneur_live_counter_total 8" in reg.render_prometheus()

    def test_broken_collector_is_skipped(self):
        reg = Registry()
        reg.add_collector(lambda: 1 / 0)
        reg.gauge("ok", 1)
        assert "veneur_ok 1" in reg.render_prometheus()


class TestPromFormat:
    def test_name_sanitization(self):
        assert prom_name("flush.total_duration_ns") == \
            "veneur_flush_total_duration_ns"
        assert prom_name("a-b.c d", "counter") == "veneur_a_b_c_d_total"
        assert prom_name("worker.metrics_processed_total", "counter") == \
            "veneur_worker_metrics_processed_total"
        assert prom_name("1weird") == "veneur__1weird"

    def test_label_escaping(self):
        labels = prom_labels(['path:a\\b', 'msg:say "hi"\nok', 'bareflag'])
        assert 'path="a\\\\b"' in labels
        assert 'msg="say \\"hi\\"\\nok"' in labels
        assert 'tag="bareflag"' in labels
        assert prom_labels([]) == ""
        # label keys are sanitized too
        assert prom_labels(["bad-key:v"]) == '{bad_key="v"}'

    def test_exposition_is_structurally_valid(self):
        reg = Registry()
        reg.count("a.total", 2, ["k:v"])
        reg.gauge("b.value", -1.5)
        reg.observe("c.lat", 0.42, ["sink:x", "status:ok"])
        text = reg.render_prometheus()
        assert_valid_exposition(text)
        assert "# TYPE veneur_a_total counter" in text
        assert "# TYPE veneur_b_value gauge" in text
        assert "# TYPE veneur_c_lat histogram" in text
        assert 'veneur_c_lat_bucket{sink="x",status="ok",le="+Inf"} 1' \
            in text


class TestScopedClientTee:
    def test_scoped_client_tees_into_registry(self):
        reg = Registry()
        packets = []
        client = ScopedClient(packet_cb=packets.append, registry=reg,
                              additional_tags=["svc:veneur"])
        client.count("c", 2, tags=["x:y"])
        client.gauge("g", 1.5)
        client.timing("t", 0.125)
        assert packets  # push side unchanged
        snap = reg.snapshot()
        # registry keeps the caller's tags, not additional/scope tags
        assert snap["counters"]["c|x:y"] == 2
        assert snap["gauges"]["g"] == 1.5
        assert snap["histograms"]["t"] == 1

    def test_null_client_still_captures(self):
        reg = Registry()
        client = NullClient(registry=reg)
        client.count("dropped.push", 7)
        assert reg.snapshot()["counters"]["dropped.push"] == 7


class TestEventRecorder:
    def test_ring_bounds_under_soak(self):
        rec = EventRecorder(capacity=128)
        for i in range(10_000):
            rec.record("tick", i=i)
        assert len(rec) == 128
        events = rec.snapshot()
        assert len(events) == 128
        assert rec.total_recorded == 10_000
        # newest-last, oldest dropped, seq contiguous across the wrap
        assert events[-1]["i"] == 9_999
        assert events[0]["seq"] == 10_000 - 128 + 1
        assert [e["seq"] for e in events] == \
            list(range(9_873, 10_001))

    def test_snapshot_limit(self):
        rec = EventRecorder(capacity=16)
        for i in range(5):
            rec.record("e", i=i)
        assert [e["i"] for e in rec.snapshot(limit=2)] == [3, 4]

    def test_concurrent_recording_stays_bounded(self):
        rec = EventRecorder(capacity=64)

        def pound():
            for i in range(2_000):
                rec.record("x", i=i)

        threads = [threading.Thread(target=pound) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(rec) == 64
        assert rec.total_recorded == 8_000


class TestFlushRecorder:
    def test_bounded_rounds(self):
        rec = FlushRecorder(capacity=8)
        for i in range(100):
            rec.record({"flush": i, "sinks": {}})
        rounds = rec.snapshot()
        assert len(rounds) == 8
        assert rounds[-1]["flush"] == 99

    def test_late_sink_outcome_lands(self):
        rec = FlushRecorder(capacity=4)
        outcome = {"status": "timed_out"}
        rec.record({"flush": 1, "sinks": {"metric:slow": outcome}})
        outcome["status"] = "ok"
        outcome["late"] = True
        got = rec.snapshot()[0]["sinks"]["metric:slow"]
        assert got["status"] == "ok" and got["late"] is True


class FailingSink(MetricSink):
    def name(self):
        return "failing"

    def kind(self):
        return "failing"

    def flush(self, metrics):
        raise RuntimeError("deliberate sink failure")


class BlockingSink(MetricSink):
    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def name(self):
        return "blocking"

    def kind(self):
        return "blocking"

    def flush(self, metrics):
        self.entered.set()
        self.release.wait(10.0)


class TestFlushOutcomeRecording:
    def test_ok_round_with_phases(self):
        server, observer = setup_server()
        server.handle_metric_packet(b"a.total:5|c")
        server.flush()
        rounds = server.telemetry.flushes.snapshot()
        assert len(rounds) == 1
        rnd = rounds[0]
        assert rnd["flush"] == 1
        assert rnd["metrics_flushed"] >= 1
        for phase in ("store_flush_s", "preflush_s", "sink_join_s"):
            assert phase in rnd["phases"]
        chan = rnd["sinks"]["metric:channel"]
        assert chan["status"] == "ok"
        assert chan["duration_s"] >= 0.0
        kinds = {e["kind"] for e in server.telemetry.events.snapshot()}
        assert "flush" in kinds

    def test_failed_sink_flush_is_recorded(self):
        server, observer = setup_server()
        server.metric_sinks.append(FailingSink())
        server.handle_metric_packet(b"a.total:5|c")
        server.flush()
        rnd = server.telemetry.flushes.snapshot()[-1]
        assert rnd["sinks"]["metric:failing"]["status"] == "error"
        assert rnd["sinks"]["metric:channel"]["status"] == "ok"
        errors = [e for e in server.telemetry.events.snapshot()
                  if e["kind"] == "sink_error"]
        assert errors and errors[0]["sink"] == "metric:failing"
        # the per-sink duration self-metric carries the error status
        snap = server.telemetry.registry.snapshot()
        assert any(k.startswith("flush.sink_duration|")
                   and "status:error" in k and "sink:metric:failing" in k
                   for k in snap["histograms"])

    def test_timed_out_then_skipped_then_late(self):
        blocking = BlockingSink()
        server, observer = setup_server()
        server.metric_sinks.append(blocking)
        try:
            server.handle_metric_packet(b"a.total:1|c")
            server.flush()  # blocks until the 0.2s interval deadline
            assert blocking.entered.wait(5.0)
            rnd1 = server.telemetry.flushes.snapshot()[-1]
            assert rnd1["sinks"]["metric:blocking"]["status"] == "timed_out"
            kinds = {e["kind"] for e in server.telemetry.events.snapshot()}
            assert "sink_timeout" in kinds

            # next round: the previous flush thread is still alive, so
            # the sink is skipped (its own data, not the flush loop's)
            server.handle_metric_packet(b"a.total:1|c")
            server.flush()
            rnd2 = server.telemetry.flushes.snapshot()[-1]
            assert rnd2["sinks"]["metric:blocking"]["status"] == "skipped"
            kinds = {e["kind"] for e in server.telemetry.events.snapshot()}
            assert "sink_skipped" in kinds
        finally:
            blocking.release.set()
        # the straggler finally lands its real outcome, flagged late
        thread = server._sink_flush_threads["metric:blocking"]
        thread.join(5.0)
        rnd1 = server.telemetry.flushes.snapshot()[0]
        assert rnd1["sinks"]["metric:blocking"]["status"] == "ok"
        assert rnd1["sinks"]["metric:blocking"]["late"] is True


def api_url(api, path):
    host, port = api.address
    return f"http://{host}:{port}{path}"


class TestPullEndpoints:
    def test_metrics_events_flush_routes(self):
        server, observer = setup_server(http_address="127.0.0.1:0")
        server.metric_sinks.append(FailingSink())
        server.start()
        try:
            for i in range(10):
                server.handle_metric_packet(b"req.count:1|c")
            server.flush()
            status, body = vhttp.get(api_url(server.http_api, "/metrics"))
            assert status == 200
            text = body.decode()
            assert_valid_exposition(text)
            # live ingest counters, scrape-time fresh
            assert re.search(
                r"^veneur_ingest_packets_received_total 1[0-9]*$",
                text, re.M)
            # flush phase timings + per-sink durations from the tee
            assert "# TYPE veneur_flush_phase_duration histogram" in text
            assert 'phase="store_flush_s"' in text
            assert re.search(
                r'veneur_flush_sink_duration_count\{sink="metric:channel",'
                r'status="ok"\} [1-9]', text)
            assert "veneur_flush_rounds_total" in text

            status, body = vhttp.get(
                api_url(server.http_api, "/debug/events"))
            assert status == 200
            events = json.loads(body)["events"]
            kinds = [e["kind"] for e in events]
            assert "startup" in kinds and "flush" in kinds
            # the most recent flush round replays, including the
            # deliberately-failed sink flush
            flush_events = [e for e in events if e["kind"] == "flush"]
            assert flush_events[-1]["sinks"]["metric:failing"] == "error"
            assert any(e["kind"] == "sink_error"
                       and e["sink"] == "metric:failing" for e in events)

            status, body = vhttp.get(
                api_url(server.http_api, "/debug/flush?n=5"))
            assert status == 200
            rounds = json.loads(body)["rounds"]
            assert rounds and "phases" in rounds[-1]
            assert rounds[-1]["sinks"]["metric:failing"]["status"] == \
                "error"
        finally:
            server.shutdown()

    def test_standalone_api_serves_metrics(self):
        # proxy-style: no server object, private telemetry
        from veneur_tpu.core.httpapi import HTTPApi
        api = HTTPApi(generate_config(), server=None,
                      address="127.0.0.1:0")
        api.start()
        try:
            status, body = vhttp.get(api_url(api, "/metrics"))
            assert status == 200
            assert_valid_exposition(body.decode())
            status, body = vhttp.get(api_url(api, "/debug/events"))
            assert status == 200 and json.loads(body)["events"] == []
        finally:
            api.stop()

    def test_device_memory_rows_shape(self):
        rows = telemetry.device_memory_rows()
        # CPU devices report no memory stats; on TPU each row must be a
        # well-formed gauge with device+platform tags
        assert isinstance(rows, list)
        for name, kind, value, tags in rows:
            assert name.startswith("device.") and kind == "gauge"
            assert any(t.startswith("device:") for t in tags)

    def test_device_rows_render_via_collector(self):
        # exercise the scrape-time device-gauge path with fabricated
        # rows (CPU backends return no memory_stats)
        tel = Telemetry()
        tel.registry.add_collector(lambda: [
            ("device.bytes_in_use", "gauge", 123456.0,
             ["device:0", "platform:tpu"]),
            ("device.bytes_limit", "gauge", 1 << 30,
             ["device:0", "platform:tpu"]),
        ])
        text = tel.registry.render_prometheus()
        assert_valid_exposition(text)
        assert ('veneur_device_bytes_in_use'
                '{device="0",platform="tpu"} 123456') in text


class TestRegistrySoakBounded:
    def test_10k_event_soak_memory_bounded(self):
        """Acceptance: registry memory stays bounded (ring buffer +
        capped histogram bins) under a 10k-event soak."""
        tel = Telemetry(max_series=256, event_capacity=512)
        for i in range(10_000):
            tel.record_event("soak", i=i)
            tel.registry.count(f"soak.counter.{i % 1000}")
            tel.registry.observe("soak.latency", (i % 100) * 0.001,
                                 tags=[f"shard:{i % 50}"])
            tel.flushes.record({"flush": i, "sinks": {}})
        assert len(tel.events) == 512
        assert len(tel.flushes) == 64
        reg = tel.registry
        assert reg._series_count() <= 256
        assert reg.series_dropped > 0
        # every histogram series holds the same fixed bin count
        for hist in reg._histograms.values():
            assert len(hist.buckets) == len(HISTOGRAM_BOUNDS) + 1
        # the whole thing still renders
        assert_valid_exposition(reg.render_prometheus())


class TestDiagnosticsSatellites:
    def test_uptime_counts_interval_delta(self):
        from veneur_tpu.core.diagnostics import collect
        calls = []

        class FakeStatsd:
            def gauge(self, name, value, tags=None):
                calls.append((name, value))

            def count(self, name, value, tags=None):
                calls.append((name, value))

        start = time.time() - 5.0
        tick = collect(FakeStatsd(), start, include_device=False)
        first = dict(calls)["uptime_ms"]
        assert first >= 5000  # first tick: since start
        calls.clear()
        time.sleep(0.05)
        collect(FakeStatsd(), start, include_device=False, last_tick=tick)
        second = dict(calls)["uptime_ms"]
        # delta since the previous tick, NOT the total again
        assert 40 <= second < 2000

    def test_rss_current_and_peak(self):
        from veneur_tpu.core.diagnostics import collect
        calls = []

        class FakeStatsd:
            def gauge(self, name, value, tags=None):
                calls.append((name, value))

            def count(self, name, value, tags=None):
                calls.append((name, value))

        collect(FakeStatsd(), time.time(), include_device=False)
        by = dict(calls)
        assert by["mem.rss_bytes"] > 0
        assert by["mem.max_rss_bytes"] > 0
        # current RSS can't exceed the high-water mark
        assert by["mem.rss_bytes"] <= by["mem.max_rss_bytes"]

    def test_loop_logs_failures_rate_limited(self, caplog):
        from veneur_tpu.core.diagnostics import DiagnosticsLoop

        class Exploding:
            def gauge(self, *a, **kw):
                raise RuntimeError("collector down")

            def count(self, *a, **kw):
                raise RuntimeError("collector down")

        loop = DiagnosticsLoop(Exploding(), interval=0.01,
                               include_device=False)
        with caplog.at_level("ERROR", logger="veneur_tpu.diagnostics"):
            loop.start()
            time.sleep(0.25)
            loop.stop()
        assert loop.errors >= 3  # kept failing, kept running
        records = [r for r in caplog.records
                   if "diagnostics collection failed" in r.message]
        assert len(records) == 1  # rate-limited to one log per window


class TestMetricNameLint:
    def _run(self, argv):
        sys.path.insert(0, str(pathlib.Path(__file__).parent.parent
                               / "scripts"))
        try:
            import check_metric_names
            return check_metric_names.main(argv)
        finally:
            sys.path.pop(0)

    def test_repo_inventory_is_complete(self, capsys):
        assert self._run([]) == 0
        assert "all documented" in capsys.readouterr().out

    def test_undocumented_metric_fails(self, tmp_path, capsys):
        pkg = tmp_path / "veneur_tpu"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            "def f(statsd):\n"
            "    statsd.count('documented.metric', 1)\n"
            "    statsd.gauge('undocumented.metric', 2)\n")
        (tmp_path / "README.md").write_text(
            "## Self-metric inventory\n\n"
            "| `documented.metric` | count |\n")
        assert self._run(["--repo", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "undocumented.metric" in out
        assert "documented.metric" not in \
            out.replace("undocumented.metric", "")

    def test_missing_docs_section_fails(self, tmp_path):
        (tmp_path / "veneur_tpu").mkdir()
        (tmp_path / "README.md").write_text("# nothing here\n")
        assert self._run(["--repo", str(tmp_path)]) == 2
