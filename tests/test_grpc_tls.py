"""mTLS on the gRPC forward plane and per-RPC latency stats
(reference proxy/proxy.go:33-120 TLS termination, proxy/grpcstats, and
the testdata-cert pattern of server_test.go:561-1052)."""

import os
import time

import grpc
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.forward.client import ForwardClient
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.forward.server import ImportServer
from veneur_tpu.proxy.proxy import create_static_proxy
from veneur_tpu.sinks.channel import ChannelMetricSink
from veneur_tpu.util.grpcstats import RpcStats
from veneur_tpu.util.grpctls import GrpcTLS

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")


def tdpath(name):
    return os.path.join(TESTDATA, name)


SERVER_TLS = GrpcTLS(certificate=tdpath("server.pem"),
                     key=tdpath("server.key"),
                     authority=tdpath("ca.pem"))
CLIENT_TLS = GrpcTLS(certificate=tdpath("client.pem"),
                     key=tdpath("client.key"),
                     authority=tdpath("ca.pem"))


def make_global(**overrides):
    cfg = Config()
    cfg.interval = 10.0
    cfg.hostname = "tls-test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    cfg.apply_defaults()
    observer = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[observer]), observer


def counter_proto(name, value):
    pbm = metric_pb2.Metric()
    pbm.name = name
    pbm.type = metric_pb2.Counter
    pbm.scope = metric_pb2.Global
    pbm.counter.value = value
    return pbm


class TestForwardPlaneTLS:
    def test_mutual_tls_forward_roundtrip(self):
        server, _obs = make_global()
        imp = ImportServer(server, "localhost:0", tls=SERVER_TLS)
        imp.start()
        try:
            client = ForwardClient(f"localhost:{imp.port}", deadline=10.0,
                                   tls=CLIENT_TLS)
            n = client.send_protos([counter_proto("tls.fwd", 7)])
            assert n == 1
            deadline = time.time() + 5
            while imp.imported_total < 1 and time.time() < deadline:
                time.sleep(0.05)
            assert imp.imported_total == 1
            client.close()
        finally:
            imp.stop()

    def test_client_without_certs_rejected(self):
        server, _obs = make_global()
        imp = ImportServer(server, "localhost:0", tls=SERVER_TLS)
        imp.start()
        try:
            # CA only, no client cert: the server requires client auth
            bare = ForwardClient(
                f"localhost:{imp.port}", deadline=3.0,
                tls=GrpcTLS(authority=tdpath("ca.pem")))
            with pytest.raises(grpc.RpcError):
                bare.send_protos([counter_proto("tls.nope", 1)])
            bare.close()
            assert imp.imported_total == 0
        finally:
            imp.stop()

    def test_plaintext_client_rejected(self):
        server, _obs = make_global()
        imp = ImportServer(server, "localhost:0", tls=SERVER_TLS)
        imp.start()
        try:
            plain = ForwardClient(f"localhost:{imp.port}", deadline=3.0)
            with pytest.raises(grpc.RpcError):
                plain.send_protos([counter_proto("tls.plain", 1)])
            plain.close()
            assert imp.imported_total == 0
        finally:
            imp.stop()


class TestProxyTLS:
    def test_proxy_terminates_tls_and_dials_tls(self):
        """Client --mTLS--> proxy --mTLS--> global import server."""
        server, _obs = make_global()
        imp = ImportServer(server, "localhost:0", tls=SERVER_TLS)
        imp.start()
        proxy = create_static_proxy(
            [f"localhost:{imp.port}"], listen_address="localhost:0",
            tls=SERVER_TLS, destination_tls=CLIENT_TLS)
        proxy.start()
        try:
            client = ForwardClient(f"localhost:{proxy.port}", deadline=10.0,
                                   tls=CLIENT_TLS)
            client.send_protos(
                [counter_proto(f"tls.proxy.{i}", i) for i in range(10)])
            client.close()
            deadline = time.time() + 8
            while imp.imported_total < 10 and time.time() < deadline:
                time.sleep(0.05)
            assert imp.imported_total == 10
            assert proxy.stats["routed_total"] == 10
            # per-RPC latency stats recorded (reference proxy/grpcstats)
            snap = proxy.rpc_stats.snapshot()
            assert snap["SendMetricsV2"]["count"] == 1
            assert snap["SendMetricsV2"]["errors"] == 0
            assert snap["SendMetricsV2"]["max_s"] > 0
        finally:
            proxy.stop()
            imp.stop()


class TestRpcStats:
    def test_timed_records_success_and_error(self):
        stats = RpcStats()
        ok = stats.timed("M", lambda req, ctx: "done")
        assert ok(None, None) == "done"
        boom = stats.timed("M", lambda req, ctx: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            boom(None, None)
        snap = stats.snapshot()["M"]
        assert snap["count"] == 2
        assert snap["errors"] == 1
        assert snap["total_s"] >= 0

    def test_emit_surface(self):
        calls = []

        class FakeStatsd:
            def count(self, name, value, tags=None):
                calls.append(("count", name, value, tuple(tags or ())))

            def gauge(self, name, value, tags=None):
                calls.append(("gauge", name, value, tuple(tags or ())))

        stats = RpcStats()
        stats.record("SendMetricsV2", 0.01, ok=True)
        stats.record("SendMetricsV2", 0.03, ok=False)
        stats.emit(FakeStatsd(), prefix="import.rpc")
        names = {c[1] for c in calls}
        assert names == {"import.rpc.count", "import.rpc.errors",
                         "import.rpc.avg_duration_ns",
                         "import.rpc.max_duration_ns"}
        by_name = {c[1]: c for c in calls}
        assert by_name["import.rpc.count"][2] == 2
        assert by_name["import.rpc.errors"][2] == 1
        assert by_name["import.rpc.max_duration_ns"][2] == int(0.03 * 1e9)
        assert by_name["import.rpc.count"][3] == ("method:SendMetricsV2",)


class TestServerConfigTLS:
    def test_import_server_tls_from_config(self):
        """grpc_tls_* config terminates TLS on the import plane; the
        local's forward_tls_* dial it with client certs."""
        cfg_over = dict(
            grpc_address="localhost:0",
            grpc_tls_certificate=tdpath("server.pem"),
            grpc_tls_authority_certificate=tdpath("ca.pem"),
        )
        glob, obs = make_global(**cfg_over)
        from veneur_tpu.util.secret import StringSecret
        glob.config.grpc_tls_key = StringSecret(tdpath("server.key"))
        glob.start()
        try:
            addr = glob.import_server.address
            local_cfg_over = dict(
                forward_address=addr,
                forward_tls_certificate=tdpath("client.pem"),
                forward_tls_authority_certificate=tdpath("ca.pem"),
            )
            local, _ = make_global(**local_cfg_over)
            local.config.forward_tls_key = StringSecret(tdpath("client.key"))
            local.start()
            try:
                local.handle_metric_packet(b"cfg.tls:4|c|#veneurglobalonly")
                local.flush()
                deadline = time.time() + 8
                while (glob.import_server.imported_total < 1
                       and time.time() < deadline):
                    time.sleep(0.05)
                assert glob.import_server.imported_total == 1
                # the V1 bulk body crossed the mTLS channel (the
                # client's preferred path; V2 streams are the fallback)
                snap = glob.import_server.rpc_stats.snapshot()
                assert snap["SendMetrics"]["count"] == 1
                assert snap["SendMetrics"]["errors"] == 0
            finally:
                local.shutdown()
        finally:
            glob.shutdown()
