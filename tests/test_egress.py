"""Golden byte-parity for the columnar egress plane (core/egress.py).

The columnar encoders must emit exactly what the legacy per-InterMetric
paths emit for the SAME FlushBatch — byte-identical for Prometheus
exposition and Cortex remote-write wire, JSON key-order-normalized for
Datadog (the series-object key order legitimately differs; JSON objects
are unordered). The batches come from the real flusher over a mixed
corpus so every family is covered: counters, gauges, timer percentile
gauges + aggregate counters, set-cardinality gauges, and llhist
percentile/sum/count plus the cumulative `.bucket{le:}` matrix.
`extras` add the legacy-only shapes: status checks, hostname-carrying
rows, and WAL-backfilled timestamp lines.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from veneur_tpu.core.columnstore import ColumnStore
from veneur_tpu.core.egress import (
    CortexColumnarEncoder, DatadogColumnarEncoder,
    PrometheusColumnarRenderer,
)
from veneur_tpu.core.flusher import flush_columnstore_batch
from veneur_tpu.samplers.metrics import (
    HistogramAggregates, InterMetric, MetricType,
)
from veneur_tpu.samplers.parser import Parser
from veneur_tpu.sinks.cortex import CortexMetricSink, encode_write_request
from veneur_tpu.sinks.datadog import DatadogMetricSink
from veneur_tpu.sinks.prometheus import (
    PrometheusMetricSink, render_exposition,
)

pytestmark = pytest.mark.egress

PCTS = (0.5, 0.99)
AGGS = HistogramAggregates.from_names(["min", "max", "count"])


def _mk_batch(extras=(), is_local=False):
    # global mode by default: mixed-scope llhists EMIT (bucket sections
    # in the batch) instead of forwarding; forward tests pass True
    store = ColumnStore(counter_capacity=64, gauge_capacity=64,
                        histo_capacity=64, set_capacity=32, batch_cap=256)
    p = Parser()
    lines = []
    for i in range(5):
        lines.append(b"c.%d:%d|c|#env:t,i:%d" % (i, i + 1, i))
        lines.append(b"g.%d:%.2f|g|#env:t" % (i, i * 1.5))
        lines.append(b"t.%d:%.2f|ms|#env:t" % (i, 10.0 + i))
        lines.append(b"t.%d:%.2f|ms|#env:t" % (i, 20.0 + i))
        lines.append(b"s.%d:user%d|s|#env:t" % (i, i))
        lines.append(b"ll.%d:%.3f|l|#env:t,svc:x" % (i, 5.0 + i))
        lines.append(b"ll.%d:%.3f|l|#env:t,svc:x" % (i, 500.0 + i))
    # tag-free rows, host:/device: magic tags, drop-prefix candidates
    lines += [
        b"bare:3|c",
        b"hosted:4|c|#host:other,device:sda,env:t",
        b"dropme.x:1|c|#env:t",
        b"ll.bare:42.5|l",
    ]
    for line in lines:
        p.parse_metric_fast(line, store.process)
    store.apply_all_pending()
    batch, fwd = flush_columnstore_batch(store, is_local, PCTS, AGGS,
                                         collect_forward=is_local)
    batch.extras.extend(extras)
    return batch, fwd


def _extras():
    return [
        InterMetric(name="extra.count", timestamp=1700000000, value=4.0,
                    tags=["q:r"], type=MetricType.COUNTER, hostname="hX"),
        InterMetric(name="svc.ok", timestamp=1700000001, value=1.0,
                    tags=["chk:y"], type=MetricType.STATUS,
                    hostname="hX", message="degraded"),
        InterMetric(name="backfill.g", timestamp=1699990000, value=7.5,
                    tags=["o:p"], type=MetricType.GAUGE, hostname="hB",
                    backfilled=True),
        InterMetric(name="backfill.c", timestamp=1699990000, value=2.0,
                    tags=[], type=MetricType.COUNTER, backfilled=True),
    ]


def _dd_sink(**kw):
    kw.setdefault("tags", ["glob:t"])
    kw.setdefault("metric_name_prefix_drops", ["dropme."])
    kw.setdefault("excluded_tag_prefixes", ["i:"])
    return DatadogMetricSink("datadog", "key", "https://dd.example", "me",
                             10.0, **kw)


# -- Datadog ---------------------------------------------------------------


def test_datadog_parity_normalized():
    batch, _ = _mk_batch(_extras())
    sink = _dd_sink()
    parts, checks = DatadogColumnarEncoder(sink).encode(batch)
    col = [json.loads(p) for p in parts]
    leg = json.loads(json.dumps([
        sink._dd_metric(m) for m in batch.materialize()
        if m.type != MetricType.STATUS
        and not m.name.startswith("dropme.")]))
    assert col == leg  # same objects in the same ORDER
    assert [c.name for c in checks] == ["svc.ok"]


def test_datadog_flush_columnar_posts_same_series(monkeypatch):
    """End to end through flush_batch: the raw byte-assembled bodies
    decode to the same series the legacy dict+json.dumps flush posts."""
    from veneur_tpu.sinks import datadog as ddmod

    posted = []

    def fake_post(url, body, **kw):
        # vhttp.post gzips internally; the fake sees the raw body
        posted.append((url, bytes(body)))

    def fake_post_json(url, payload, **kw):
        posted.append((url, json.dumps(payload).encode()))

    monkeypatch.setattr(ddmod.vhttp, "post", fake_post)
    monkeypatch.setattr(ddmod.vhttp, "post_json", fake_post_json)
    batch, _ = _mk_batch(_extras())
    sink = _dd_sink(num_workers=1)
    sink.flush_batch(batch)
    col_series = [json.loads(b)["series"] for u, b in posted
                  if "/series" in u]
    col_checks = [json.loads(b) for u, b in posted if "check_run" in u]
    posted.clear()
    sink2 = _dd_sink(num_workers=1)
    sink2.flush(batch.materialize())
    leg_series = [json.loads(b)["series"] for u, b in posted
                  if "/series" in u]
    leg_checks = [json.loads(b) for u, b in posted if "check_run" in u]
    assert col_series == leg_series
    assert col_checks == leg_checks


def test_datadog_columnar_fallback_on_encoder_error(monkeypatch):
    from veneur_tpu.sinks import datadog as ddmod

    calls = []
    monkeypatch.setattr(ddmod.vhttp, "post",
                        lambda *a, **k: calls.append("raw"))
    monkeypatch.setattr(ddmod.vhttp, "post_json",
                        lambda *a, **k: calls.append("json"))
    batch, _ = _mk_batch()
    sink = _dd_sink(num_workers=1)
    from veneur_tpu.core import egress as egmod
    monkeypatch.setattr(
        egmod.DatadogColumnarEncoder, "encode",
        lambda self, b: (_ for _ in ()).throw(RuntimeError("boom")))
    sink.flush_batch(batch)  # must not raise; legacy path delivers
    assert "json" in calls


# -- Prometheus ------------------------------------------------------------


def _fake_exemplars(clauses):
    def exemplars(name, tags):
        return clauses.get(name, "")
    return exemplars


def test_prometheus_parity_plain_and_openmetrics():
    batch, _ = _mk_batch(_extras())
    legacy = batch.materialize()
    r = PrometheusColumnarRenderer()
    assert r.render(batch) == render_exposition(legacy)
    ex = _fake_exemplars({
        "c.0": ' # {trace_id="ab"} 1.0 1700000000.000',
        "ll.1.bucket": ' # {trace_id="cd"} 501.0 1700000000.000',
        "extra.count": ' # {trace_id="ef"} 4.0 1700000000.000',
    })
    for om in (False, True):
        got = PrometheusColumnarRenderer().render(
            batch, exemplars=ex, openmetrics=om)
        want = render_exposition(legacy, exemplars=ex, openmetrics=om)
        assert got == want
    # the suite must actually exercise the clauses + backfilled stamps
    om_text = render_exposition(legacy, exemplars=ex, openmetrics=True)
    assert '# {trace_id="ab"}' in om_text
    assert '# {trace_id="cd"}' in om_text
    assert "backfill_g" in om_text and " 1699990000" in om_text


def test_prometheus_sink_columnar_exposition():
    batch, _ = _mk_batch(_extras())
    sink = PrometheusMetricSink("prometheus")
    sink.flush_batch(batch)
    assert sink.exposition_plain() == render_exposition(
        batch.materialize())
    # lazy OM render comes from the stored batch
    assert sink.exposition_openmetrics() == render_exposition(
        batch.materialize(), openmetrics=True) + "# EOF\n"


def test_prometheus_repeater_falls_back_to_legacy(monkeypatch):
    batch, _ = _mk_batch()
    sink = PrometheusMetricSink("prometheus",
                                repeater_address="127.0.0.1:1",
                                network="udp")
    sink.flush_batch(batch)  # repeater wants InterMetrics; no raise
    assert sink.exposition_plain() == render_exposition(
        batch.materialize())


# -- Cortex ----------------------------------------------------------------


class _FakeExemplarStore:
    def __init__(self, entries):
        self.entries = entries  # name -> (trace_id, value, ts)

    def for_series(self, name, tags=()):
        return self.entries.get(name)


def _cortex_series(sink, metrics):
    exemplified = set()
    series = []
    for m in metrics:
        if m.type == MetricType.STATUS:
            continue
        if (m.type == MetricType.COUNTER
                and sink.convert_counters_to_monotonic):
            key = (m.name, tuple(sorted(m.tags)), m.hostname)
            sink._monotonic[key] = (
                sink._monotonic.get(key, 0.0) + float(m.value))
            continue
        row = sink._series(m)
        entry = sink._exemplar_entry(m, exemplified)
        if entry is not None:
            from veneur_tpu.trace.store import trace_id_hex
            tid, ev, ets = entry
            row = row + ((trace_id_hex(tid), float(ev), int(ets * 1000)),)
        series.append(row)
    return series


def test_cortex_parity_bytes():
    batch, _ = _mk_batch(_extras())
    sink = CortexMetricSink("cortex", "http://c/api", "myhost",
                            excluded_tags=["i"])
    sink._exemplars = _FakeExemplarStore({
        "c.0": (0xAB, 1.5, 1700000000.25),
        "extra.count": (0xEF, 4.0, 1700000001.0),
    })
    frames, max_ts = CortexColumnarEncoder(sink).encode(batch)
    legacy = batch.materialize()
    sink2 = CortexMetricSink("cortex", "http://c/api", "myhost",
                             excluded_tags=["i"])
    sink2._exemplars = sink._exemplars
    want = encode_write_request(_cortex_series(sink2, legacy))
    assert b"".join(frames) == want
    assert max_ts == max(m.timestamp for m in legacy)


def test_cortex_parity_monotonic_mode():
    batch, _ = _mk_batch(_extras())
    col = CortexMetricSink("cortex", "http://c/api", "myhost",
                           convert_counters_to_monotonic=True)
    leg = CortexMetricSink("cortex", "http://c/api", "myhost",
                           convert_counters_to_monotonic=True)
    frames, max_ts = CortexColumnarEncoder(col).encode(batch)
    series = _cortex_series(leg, batch.materialize())
    assert b"".join(frames) == encode_write_request(series)
    assert col._monotonic == leg._monotonic  # counters + buckets folded
    assert any("le:+Inf" in k[1] for k in col._monotonic)
    # the re-emit stamp comes from the SAME fold, legacy-compatible
    assert max_ts == max(m.timestamp for m in batch.materialize())
    col_frames = [encode_write_request([r])
                  for r in col._monotonic_series(max_ts)]
    leg_frames = [encode_write_request([r])
                  for r in leg._monotonic_series(max_ts)]
    assert b"".join(col_frames) == b"".join(leg_frames)


def test_cortex_flush_columnar_posts_same_bytes(monkeypatch):
    from veneur_tpu.sinks import cortex as cxmod

    posted = []
    monkeypatch.setattr(
        cxmod.vhttp, "post",
        lambda url, body, **kw: posted.append(bytes(body)))
    batch, _ = _mk_batch(_extras())
    sink = CortexMetricSink("cortex", "http://c/api", "myhost",
                            batch_write_size=7)
    sink.flush_batch(batch)
    col = list(posted)
    posted.clear()
    sink2 = CortexMetricSink("cortex", "http://c/api", "myhost",
                             batch_write_size=7)
    sink2.flush(batch.materialize())
    assert col == posted  # chunk boundaries AND bytes identical


# -- streaming forward (pre-encoded wire) ----------------------------------


def test_forward_wire_prebuilt_matches_reencode():
    _, fwd = _mk_batch(is_local=True)
    from veneur_tpu.forward.convert import forwardable_to_wire

    assert len(fwd)
    first = forwardable_to_wire(fwd)
    fwd.wire = first
    assert forwardable_to_wire(fwd) == first  # deterministic
    fwd.invalidate_wire()
    assert fwd.wire is None


def test_carryover_merge_invalidates_wire():
    from veneur_tpu.forward.convert import forwardable_to_wire
    from veneur_tpu.util.resilience import Carryover

    _, fwd_a = _mk_batch(is_local=True)
    _, fwd_b = _mk_batch(is_local=True)
    co = Carryover(max_intervals=4)
    fwd_a.wire = forwardable_to_wire(fwd_a)
    co.stash(fwd_a)
    fwd_b.wire = forwardable_to_wire(fwd_b)
    merged = co.drain_into(fwd_b)
    assert merged.wire is None  # stale frames must not be sent
    # stash-merge path too: pending + new both had wire set
    fwd_b.wire = forwardable_to_wire(fwd_b)
    co.stash(fwd_b)
    _, fwd_c = _mk_batch(is_local=True)
    fwd_c.wire = forwardable_to_wire(fwd_c)
    co.stash(fwd_c)
    assert co._pending.wire is None


# -- encode/send observability ---------------------------------------------


def test_note_egress_rows_in_observatory():
    from veneur_tpu.core.latency import LatencyObservatory

    obs = LatencyObservatory(enabled=True)
    obs.note_egress("datadog", 0.002, 0.030)
    obs.note_egress("datadog", 0.004, 0.010)
    obs.note_egress("cortex", 0.001, 0.020)
    rows = obs.telemetry_rows()
    names = {(n, tuple(sorted(tags))) for n, _v, _k, tags in rows}
    assert any(n == "egress.encode_s.p99" and ("sink:datadog",) == t
               for n, t in names)
    assert any(n == "egress.send_s.count" and ("sink:cortex",) == t
               for n, t in names)
    rep = obs.report()
    assert set(rep["egress"]) == {"datadog", "cortex"}
    assert rep["egress"]["datadog"]["encode"]["count"] == 2


def test_sink_note_egress_reports_and_tags_span():
    class _Lat:
        def __init__(self):
            self.calls = []

        def note_egress(self, sink, enc, snd):
            self.calls.append((sink, enc, snd))

    sink = PrometheusMetricSink("prometheus")
    lat = _Lat()
    sink._latency = lat
    sink.note_egress(0.5, 0.25)
    assert lat.calls == [("prometheus", 0.5, 0.25)]


# -- sustained churn soak --------------------------------------------------


@pytest.mark.slow
def test_egress_parity_soak():
    """Rounds of fresh flushes through LONG-lived encoders (caches warm
    and churn across rounds: id-keyed fragments must never serve stale
    bytes) stay byte-exact against the legacy renderers."""
    dd = _dd_sink()
    dd_enc = DatadogColumnarEncoder(dd)
    prom = PrometheusColumnarRenderer()
    cx = CortexMetricSink("cortex", "http://c/api", "myhost")
    cx_enc = CortexColumnarEncoder(cx)
    for round_no in range(8):
        extras = _extras() if round_no % 2 else []
        batch, _ = _mk_batch(extras)
        legacy = batch.materialize()
        parts, _checks = dd_enc.encode(batch)
        leg = json.loads(json.dumps([
            dd._dd_metric(m) for m in legacy
            if m.type != MetricType.STATUS
            and not m.name.startswith("dropme.")]))
        assert [json.loads(p) for p in parts] == leg
        assert prom.render(batch) == render_exposition(legacy)
        frames, _ = cx_enc.encode(batch)
        want = encode_write_request(
            [cx._series(m) for m in legacy
             if m.type != MetricType.STATUS])
        assert b"".join(frames) == want
