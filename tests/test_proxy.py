"""Proxy tier tests: consistent ring, destination pool, routing, discovery
(reference proxy/handlers/handlers_test.go, destinations_test.go)."""

import http.server
import json
import threading
import time

import pytest

from veneur_tpu.forward.client import ForwardClient
from veneur_tpu.forward.convert import forwardable_to_protos
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.proxy import ConsistentRing, EmptyRingError, ProxyServer
from veneur_tpu.proxy.discovery import HttpJsonDiscoverer, StaticDiscoverer
from veneur_tpu.proxy.proxy import create_static_proxy
from veneur_tpu.testing.forwardtest import ForwardTestServer
from veneur_tpu.util.matcher import TagMatcher


def wait_until(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def mkmetric(name, value=1, tags=()):
    pbm = metric_pb2.Metric(name=name, type=metric_pb2.Counter,
                            scope=metric_pb2.Global)
    pbm.tags.extend(tags)
    pbm.counter.value = value
    return pbm


class TestRing:
    def test_empty_ring_raises(self):
        with pytest.raises(EmptyRingError):
            ConsistentRing().get("x")

    def test_single_member_gets_everything(self):
        ring = ConsistentRing()
        ring.add("a:1")
        assert all(ring.get(f"k{i}") == "a:1" for i in range(100))

    def test_distribution_roughly_uniform(self):
        ring = ConsistentRing(replicas=50)
        members = [f"host{i}:8128" for i in range(4)]
        ring.set_members(members)
        counts = {m: 0 for m in members}
        for i in range(4000):
            counts[ring.get(f"metric.key.{i}")] += 1
        for member, n in counts.items():
            assert 400 < n < 2200, counts

    def test_consistency_on_removal(self):
        """Removing one of N members remaps only that member's keys."""
        ring = ConsistentRing(replicas=50)
        members = [f"host{i}:8128" for i in range(5)]
        ring.set_members(members)
        before = {f"k{i}": ring.get(f"k{i}") for i in range(2000)}
        ring.remove("host3:8128")
        moved = 0
        for key, owner in before.items():
            new = ring.get(key)
            if owner == "host3:8128":
                assert new != "host3:8128"
            elif new != owner:
                moved += 1
        assert moved == 0  # keys not owned by the removed member stay put

    def test_set_members_reconciles(self):
        ring = ConsistentRing()
        ring.set_members(["a", "b", "c"])
        ring.set_members(["b", "c", "d"])
        assert ring.members() == ["b", "c", "d"]


class TestProxyRouting:
    def _boot(self, n=2, **kwargs):
        received = [[] for _ in range(n)]
        servers = []
        for i in range(n):
            ft = ForwardTestServer(received[i].extend)
            ft.start()
            servers.append(ft)
        proxy = create_static_proxy([s.address for s in servers], **kwargs)
        proxy.start()
        return proxy, servers, received

    def test_routes_all_metrics_consistently(self):
        proxy, servers, received = self._boot(2)
        try:
            client = ForwardClient(proxy.address)
            metrics = [mkmetric(f"m.{i}", i) for i in range(50)]
            send = client._send_v2
            send(iter(metrics), timeout=5)
            assert wait_until(
                lambda: sum(len(r) for r in received) == 50)
            # both backends got a share and no metric was duplicated
            assert all(received), [len(r) for r in received]
            names = sorted(p.name for r in received for p in r)
            assert names == sorted(f"m.{i}" for i in range(50))

            # same key -> same backend on a second send
            first_owner = {p.name: i for i, r in enumerate(received)
                           for p in r}
            send(iter([mkmetric(f"m.{i}", 1) for i in range(50)]), timeout=5)
            assert wait_until(
                lambda: sum(len(r) for r in received) == 100)
            for i, r in enumerate(received):
                for p in r:
                    assert first_owner[p.name] == i
            client.close()
        finally:
            proxy.stop()
            for s in servers:
                s.stop()

    def test_ignored_tags_do_not_affect_key(self):
        proxy, servers, received = self._boot(
            2, ignore_tags=[TagMatcher(kind="prefix", value="host:")])
        try:
            client = ForwardClient(proxy.address)
            a = mkmetric("same.metric", 1, tags=["host:a", "env:prod"])
            b = mkmetric("same.metric", 2, tags=["host:b", "env:prod"])
            client._send_v2(iter([a, b]), timeout=5)
            assert wait_until(lambda: sum(len(r) for r in received) == 2)
            owners = [i for i, r in enumerate(received) for _ in r]
            assert owners[0] == owners[1]  # ignoring host: keeps them together
            client.close()
        finally:
            proxy.stop()
            for s in servers:
                s.stop()

    def test_healthcheck(self):
        proxy = ProxyServer(StaticDiscoverer([]))
        proxy.start()
        assert not proxy.healthy()
        proxy.stop()

        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        proxy2 = create_static_proxy([ft.address])
        proxy2.start()
        assert proxy2.healthy()
        proxy2.stop()
        ft.stop()

    def test_local_server_through_proxy_to_global(self):
        """Full chain: local veneur-tpu -> proxy -> global import server."""
        from test_forward import make_config
        from veneur_tpu.core.server import Server
        from veneur_tpu.sinks.channel import ChannelMetricSink

        global_cfg = make_config(grpc_address="127.0.0.1:0")
        global_obs = ChannelMetricSink()
        global_server = Server(global_cfg, extra_metric_sinks=[global_obs])
        global_server.start()
        assert wait_until(lambda: global_server.import_server is not None)

        proxy = create_static_proxy([global_server.import_server.address])
        proxy.start()

        local_cfg = make_config(forward_address=proxy.address)
        local_server = Server(local_cfg,
                              extra_metric_sinks=[ChannelMetricSink()])
        local_server.start()
        try:
            for v in (1.0, 2.0, 3.0):
                local_server.handle_metric_packet(
                    b"proxy.lat:%d|ms" % int(v))
            local_server.flush()
            assert wait_until(
                lambda: global_server.import_server.imported_total >= 1)
            # the proxy's destination sender took the V1 bulk path to
            # this framework's importer (V2 streams are the fallback for
            # reference-style receivers). The stats recorder runs after
            # the handler returns (imported_total increments inside it),
            # so poll; read before flush() drains the stats.
            assert wait_until(lambda: global_server.import_server
                              .rpc_stats.snapshot()
                              .get("SendMetrics", {}).get("count", 0) >= 1)
            global_server.flush()
            got = {m.name: m for m in global_obs.wait_flush(timeout=10)}
            assert "proxy.lat.50percentile" in got
            assert got["proxy.lat.50percentile"].value == pytest.approx(
                2.0, rel=0.25)
        finally:
            local_server.shutdown()
            proxy.stop()
            global_server.shutdown()

    def test_destination_pins_to_v2_on_refusal(self):
        """A V2-only receiver (the reference importer contract) answers
        the first V1 batch with UNIMPLEMENTED; the destination must
        deliver the SAME batch via the stream and stay on V2."""
        from veneur_tpu.forward.protos import metric_pb2
        from veneur_tpu.proxy.destinations import Destination
        from veneur_tpu.testing.forwardtest import ForwardTestServer

        got = []
        ft = ForwardTestServer(got.extend)  # V2 only
        ft.start()
        try:
            dest = Destination(ft.address, on_close=lambda d: None,
                               flush_interval=0.1)
            for i in range(3):
                dest.send(metric_pb2.Metric(
                    name=f"d{i}", type=metric_pb2.Counter,
                    counter=metric_pb2.CounterValue(value=i)))
            assert wait_until(lambda: len(got) == 3)
            assert dest._v1_ok is False
            assert dest.dropped_total == 0
            dest.send(metric_pb2.Metric(
                name="later", type=metric_pb2.Counter,
                counter=metric_pb2.CounterValue(value=9)))
            assert wait_until(lambda: len(got) == 4)
            dest.close()
        finally:
            ft.stop()


class TestNativeRouting:
    def _proxy_with_fakes(self, n_dest=3, **kwargs):
        from veneur_tpu.testing.forwardtest import ForwardTestServer
        received = [[] for _ in range(n_dest)]
        servers = []
        for i in range(n_dest):
            ft = ForwardTestServer(received[i].extend)
            ft.start()
            servers.append(ft)
        proxy = create_static_proxy([s.address for s in servers], **kwargs)
        proxy.start()  # populates the destination pool via discovery
        assert wait_until(lambda: len(proxy.destinations._pool) == n_dest)
        return proxy, servers, received

    def _body(self, metrics):
        from veneur_tpu.forward.wire import _frame_v1
        return b"".join(_frame_v1(m.SerializeToString()) for m in metrics)

    def test_native_route_matches_upb_route(self):
        """The native re-scatter must place every metric on the same
        destination the upb handle_metric path would, and deliver
        byte-identical protos."""
        from veneur_tpu.forward.protos import metric_pb2

        metrics = []
        for i in range(200):
            metrics.append(metric_pb2.Metric(
                name=f"route.{i % 37}", tags=[f"t:{i % 7}", "env:x"],
                type=(metric_pb2.Counter, metric_pb2.Gauge,
                      metric_pb2.Timer)[i % 3],
                scope=metric_pb2.Global,
                counter=metric_pb2.CounterValue(value=i)))
        body = self._body(metrics)

        # ring placement depends on member addresses, so both paths must
        # run through the SAME proxy (same ring) to compare
        proxy, servers, received = self._proxy_with_fakes()
        try:
            want = len(metrics)

            def wait_total(n):
                deadline = time.time() + 10
                while time.time() < deadline and sum(map(len,
                                                         received)) < n:
                    time.sleep(0.05)
                assert sum(map(len, received)) == n

            assert proxy._route_native(body) == (want, want)
            wait_total(want)
            native_placement = [
                sorted(m.SerializeToString() for m in dest)
                for dest in received]
            for dest in received:
                dest.clear()
            for pbm in metrics:
                proxy.handle_metric(pbm)
            wait_total(want)
            upb_placement = [
                sorted(m.SerializeToString() for m in dest)
                for dest in received]
            assert sum(len(d) for d in native_placement) == want
            assert any(native_placement), "vacuous: nothing delivered"
            assert native_placement == upb_placement
            assert len(proxy._route_cache) > 0
        finally:
            proxy.stop()
            for s in servers:
                s.stop()

    def test_ignored_tags_affect_ring_key_once(self):
        from veneur_tpu.forward.protos import metric_pb2
        from veneur_tpu.util.matcher import TagMatcher

        proxy, servers, received = self._proxy_with_fakes(
            n_dest=1, ignore_tags=[TagMatcher(kind="prefix", value="drop")])
        try:
            m1 = metric_pb2.Metric(
                name="ik", tags=["drop:a", "keep:1"],
                type=metric_pb2.Counter,
                counter=metric_pb2.CounterValue(value=1))
            proxy._route_native(self._body([m1]))
            (key, (point, _khash)), = proxy._route_cache.items()
            # ring key excludes the ignored tag, exactly like
            # handle_metric's derivation (cache stores its ring point
            # plus the per-key HLL hash for forwarded-key cardinality)
            assert point == proxy.destinations.ring.point_of(
                "ikcounterkeep:1")
        finally:
            proxy.stop()
            servers[0].stop()

    def test_invalid_utf8_rejected_not_forwarded(self):
        """A structurally-valid Metric with invalid UTF-8 in its name
        must be rejected at the proxy (the upb contract) — never batched
        with innocent metrics where it would poison a whole destination
        send downstream."""
        from veneur_tpu.forward.protos import metric_pb2
        from veneur_tpu.forward.wire import _frame_v1

        proxy, servers, received = self._proxy_with_fakes(n_dest=1)
        try:
            ok = metric_pb2.Metric(
                name="clean", type=metric_pb2.Counter,
                counter=metric_pb2.CounterValue(value=1))
            # hand-build: field 1 (name) = b"\xff", field 5 counter
            poison = b"\x0a\x01\xff\x2a\x02\x08\x01"
            body = (_frame_v1(ok.SerializeToString())
                    + _frame_v1(poison))
            with pytest.raises(Exception):  # upb DecodeError surfaces
                proxy._route_native(body)
            deadline = time.time() + 5
            while time.time() < deadline and not received[0]:
                time.sleep(0.05)
            # the clean metric was forwarded; the poison never was
            assert [m.name for m in received[0]] == ["clean"]
            assert proxy.stats["routed_total"] == 1
        finally:
            proxy.stop()
            servers[0].stop()

    def test_wide_enum_takes_upb_path(self):
        from veneur_tpu.forward.protos import metric_pb2

        proxy, servers, received = self._proxy_with_fakes(n_dest=1)
        try:
            pbm = metric_pb2.Metric(
                name="wide", counter=metric_pb2.CounterValue(value=1))
            pbm.type = 300  # beyond the identity key's byte field
            ok = metric_pb2.Metric(
                name="fine", type=metric_pb2.Counter,
                counter=metric_pb2.CounterValue(value=2))
            body = self._body([ok, pbm])
            # Type.Name(300) raises in handle_metric — same contract as
            # the stream path; the routable metric still goes through
            try:
                proxy._route_native(body)
            except ValueError:
                pass
            deadline = time.time() + 5
            while time.time() < deadline and not received[0]:
                time.sleep(0.05)
            assert [m.name for m in received[0]] == ["fine"]
        finally:
            proxy.stop()
            servers[0].stop()


class TestDiscovery:
    def test_http_json_discoverer(self):
        payload = ["10.0.0.1:8128",
                   {"Service": {"Address": "10.0.0.2", "Port": 8128}}]

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_port}/v1/health/{{service}}"
            disc = HttpJsonDiscoverer(url)
            got = disc.get_destinations_for_service("veneur-global")
            assert got == ["10.0.0.1:8128", "10.0.0.2:8128"]
        finally:
            httpd.shutdown()

    def test_discovery_refresh_updates_pool(self):
        ft1 = ForwardTestServer(lambda ms: None)
        ft1.start()
        ft2 = ForwardTestServer(lambda ms: None)
        ft2.start()
        current = [[ft1.address]]

        class FlipDiscoverer(StaticDiscoverer):
            def __init__(self):
                pass

            def get_destinations_for_service(self, service):
                return list(current[0])

        proxy = ProxyServer(FlipDiscoverer(), discovery_interval=0.1)
        proxy.start()
        try:
            assert wait_until(lambda: proxy.destinations.size() == 1)
            current[0] = [ft1.address, ft2.address]
            assert wait_until(lambda: proxy.destinations.size() == 2)
            current[0] = [ft2.address]
            assert wait_until(lambda: proxy.destinations.size() == 1)
            assert proxy.destinations.ring.members() == [ft2.address]
        finally:
            proxy.stop()
            ft1.stop()
            ft2.stop()

    def test_empty_discovery_keeps_pool(self):
        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        current = [[ft.address]]

        class FlipDiscoverer(StaticDiscoverer):
            def __init__(self):
                pass

            def get_destinations_for_service(self, service):
                return list(current[0])

        proxy = ProxyServer(FlipDiscoverer(), discovery_interval=0.1)
        proxy.start()
        try:
            assert wait_until(lambda: proxy.destinations.size() == 1)
            current[0] = []  # discovery outage must not clear the pool
            time.sleep(0.3)
            assert proxy.destinations.size() == 1
        finally:
            proxy.stop()
            ft.stop()


class TestV2ConfigShape:
    """cmd/veneur_proxy accepts the reference v2 proxy config
    (reference proxy/config.go) as well as the legacy shape."""

    def _args(self, **over):
        import types
        base = dict(config=None, destinations="", listen="127.0.0.1:0",
                    http="", discovery_interval="10s",
                    forward_service="veneur-global", tls_cert="",
                    tls_key="", tls_ca="", dest_tls_ca="",
                    dest_tls_cert="", dest_tls_key="", debug=False)
        base.update(over)
        return types.SimpleNamespace(**base)

    def test_v2_config_end_to_end(self):
        import logging
        import socket

        from veneur_tpu.cmd.veneur_proxy import build_from_config

        got = []
        ft = ForwardTestServer(got.extend)
        ft.start()
        statsd_recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        statsd_recv.bind(("127.0.0.1", 0))
        statsd_recv.settimeout(5.0)
        raw = {
            "forward_addresses": [ft.address],
            "discovery_interval": "1s",
            "forward_service": "svc-v2",
            "ignore_tags": [{"kind": "prefix", "value": "host"}],
            "send_buffer_size": 128,
            "shutdown_timeout": "1s",
            "statsd": {
                "address": "127.0.0.1:%d" % statsd_recv.getsockname()[1]},
            "runtime_metrics_interval": "200ms",
            # v2 nested blocks parse without error even though the
            # Go-runtime gRPC keepalive knobs have no Python analog
            "grpc_server": {"max_connection_idle": "10m"},
            "http": {"enable_config": False},
        }
        proxy, stats_loop, http_api = build_from_config(
            raw, self._args(), logging.getLogger("test"))
        try:
            assert proxy.forward_service == "svc-v2"
            assert stats_loop is not None and http_api is None
            # ignore_tags from yaml affect the ring key: both variants
            # of the host tag route identically (single destination, so
            # delivery is the observable)
            client = ForwardClient(proxy.address, deadline=5.0)
            client.send_protos([mkmetric("v2.cfg.m", 3, ("host:a", "svc:x"))])
            client.close()
            assert wait_until(lambda: len(got) == 1)
            assert got[0].name == "v2.cfg.m"
            # the telemetry loop emits runtime gauges + rpc aggregates
            deadline = time.time() + 5
            names = set()
            while time.time() < deadline:
                try:
                    pkt, _ = statsd_recv.recvfrom(4096)
                except OSError:
                    break
                names.add(pkt.split(b":", 1)[0])
                if b"rpc.count" in names and b"mem.rss_bytes" in names:
                    break
            assert b"mem.rss_bytes" in names
            assert b"rpc.count" in names
        finally:
            if stats_loop:
                stats_loop.stop()
            proxy.stop()
            ft.stop()
            statsd_recv.close()

    def test_dual_listener_tls_and_plaintext(self):
        import logging

        from test_grpc_tls import CLIENT_TLS, tdpath
        from veneur_tpu.cmd.veneur_proxy import build_from_config

        got = []
        ft = ForwardTestServer(got.extend)
        ft.start()
        raw = {
            "forward_addresses": [ft.address],
            "grpc_address": "127.0.0.1:0",
            "grpc_tls_address": "127.0.0.1:0",
            "tls_certificate": tdpath("server.pem"),
            "tls_key": tdpath("server.key"),
            "tls_authority_certificate": tdpath("ca.pem"),
        }
        proxy, stats_loop, http_api = build_from_config(
            raw, self._args(), logging.getLogger("test"))
        try:
            assert proxy.tls_port and proxy.port
            assert proxy.tls_port != proxy.port
            # plaintext leg
            c1 = ForwardClient(f"127.0.0.1:{proxy.port}", deadline=5.0)
            c1.send_protos([mkmetric("v2.plain", 1)])
            c1.close()
            assert wait_until(lambda: len(got) == 1)
            # TLS leg (mutual auth, hostname pinned by the test CA)
            c2 = ForwardClient(f"localhost:{proxy.tls_port}", deadline=5.0,
                               tls=CLIENT_TLS)
            c2.send_protos([mkmetric("v2.tls", 1)])
            c2.close()
            assert wait_until(lambda: len(got) == 2)
        finally:
            proxy.stop()
            ft.stop()

    def test_tls_address_without_certs_fails_loudly(self):
        import logging

        from veneur_tpu.cmd.veneur_proxy import build_from_config

        raw = {"forward_addresses": ["127.0.0.1:1"],
               "grpc_address": "127.0.0.1:0",
               "grpc_tls_address": "127.0.0.1:0"}
        with pytest.raises(ValueError, match="grpc_tls_address"):
            build_from_config(raw, self._args(), logging.getLogger("test"))

    def test_bad_shutdown_timeout_fails_at_startup(self):
        import logging

        from veneur_tpu.cmd.veneur_proxy import build_from_config

        raw = {"forward_addresses": ["127.0.0.1:1"],
               "grpc_address": "127.0.0.1:0",
               "shutdown_timeout": "not-a-duration"}
        with pytest.raises(Exception):
            build_from_config(raw, self._args(), logging.getLogger("test"))
