"""Parity tests for the pallas TPU kernels (run in interpret mode on the
CPU backend; the same kernels compile natively on TPU)."""

from __future__ import annotations

import numpy as np
import pytest

from veneur_tpu.ops import batch_hll, hll_ref, pallas_hll


class TestPallasHLLEstimate:
    def _random_regs(self, num_keys, seed=0, fill=0.3):
        rng = np.random.default_rng(seed)
        regs = np.zeros((num_keys, hll_ref.M), np.int8)
        mask = rng.random(regs.shape) < fill
        regs[mask] = rng.integers(1, 51, int(mask.sum()), dtype=np.int8)
        return regs

    def test_matches_jnp_path(self):
        regs = self._random_regs(pallas_hll.TK)
        want = np.asarray(batch_hll._estimate_jnp(regs))
        got = np.asarray(pallas_hll._estimate_pallas(regs, True))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_matches_scalar_reference(self):
        num_keys = pallas_hll.TK
        regs = np.zeros((num_keys, hll_ref.M), np.int8)
        rng = np.random.default_rng(7)
        cardinalities = [0, 1, 100, 5000]
        for row, n in enumerate(cardinalities):
            h = hll_ref.HLL()
            for i in range(n):
                h.insert(b"m%d-%d" % (row, i))
            regs[row] = h.regs
        got = np.asarray(pallas_hll._estimate_pallas(regs, True))
        for row, n in enumerate(cardinalities):
            want = hll_ref.estimate_from_registers(regs[row])
            assert got[row] == pytest.approx(want), (row, n)
            if n:
                assert got[row] == pytest.approx(n, rel=0.05), (row, n)
        # untouched rows estimate zero
        assert float(got[len(cardinalities)]) == 0.0

    def test_multi_tile(self):
        regs = self._random_regs(pallas_hll.TK * 3, seed=3, fill=0.05)
        want = np.asarray(batch_hll._estimate_jnp(regs))
        got = np.asarray(pallas_hll._estimate_pallas(regs, True))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_dispatch_falls_back_off_tpu(self):
        # on the CPU test backend estimate() must route to the jnp path
        regs = self._random_regs(pallas_hll.TK, seed=5, fill=0.1)
        want = np.asarray(batch_hll._estimate_jnp(regs))
        got = np.asarray(batch_hll.estimate(regs))
        np.testing.assert_allclose(got, want, rtol=1e-6)


class TestPallasTdigestFlush:
    """The fused flush interpolation must match the jnp path bit-for-
    tolerance across the full output layout (quantiles + FLUSH_SCALARS),
    including empty rows, single-centroid rows, and the min/max bounds
    rules (merging_digest.go:302-332)."""

    def _state_with_data(self, num_keys, seed=0):
        import jax.numpy as jnp

        from veneur_tpu.ops import batch_tdigest as btd
        rng = np.random.default_rng(seed)
        state = btd.init_state(num_keys)
        rows, vals, wts = [], [], []
        for row in range(num_keys - 2):  # leave two rows empty
            n = int(rng.integers(1, 200))
            rows.extend([row] * n)
            vals.extend(rng.normal(rng.uniform(-50, 50),
                                   rng.uniform(0.1, 20), n).tolist())
            wts.extend((rng.random(n) * 3 + 0.1).tolist())
        rows = np.asarray(rows, np.int32)
        order = np.argsort(rows, kind="stable")
        rows, vals, wts = (rows[order], np.asarray(vals, np.float32)[order],
                           np.asarray(wts, np.float32)[order])
        state = btd.apply_batch(state, rows, vals, wts)
        return state

    def test_packed_flush_matches_jnp(self):
        from veneur_tpu.ops import batch_tdigest as btd
        from veneur_tpu.ops import pallas_tdigest as ptd

        num_keys = ptd.BK
        state = self._state_with_data(num_keys, seed=3)
        ps = (0.5, 0.75, 0.99)
        want = np.asarray(btd.flush_quantiles_packed(state, ps,
                                                     fold_staging=True))
        got = np.asarray(btd.flush_quantiles_packed_pallas(
            state, ps, True, True))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-4,
                                   equal_nan=True)

    def test_export_variant_matches_jnp(self):
        from veneur_tpu.ops import batch_tdigest as btd
        from veneur_tpu.ops import pallas_tdigest as ptd

        num_keys = ptd.BK
        state = self._state_with_data(num_keys, seed=11)
        ps = (0.5, 0.99)
        want_f, want_e = btd.flush_export_packed(state, ps)
        got_f, got_e = btd.flush_export_packed_pallas(state, ps, True)
        np.testing.assert_allclose(np.asarray(got_f), np.asarray(want_f),
                                   rtol=2e-5, atol=1e-4, equal_nan=True)
        # export half is shared XLA code: identical
        np.testing.assert_allclose(np.asarray(got_e), np.asarray(want_e),
                                   rtol=1e-6, equal_nan=True)

    def test_multi_tile(self):
        from veneur_tpu.ops import batch_tdigest as btd
        from veneur_tpu.ops import pallas_tdigest as ptd

        num_keys = ptd.BK * 2
        state = self._state_with_data(num_keys, seed=5)
        ps = (0.9,)
        want = np.asarray(btd.flush_quantiles_packed(state, ps,
                                                     fold_staging=True))
        got = np.asarray(btd.flush_quantiles_packed_pallas(
            state, ps, True, True))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-4,
                                   equal_nan=True)

    def test_histo_table_platform_gate(self):
        """Off-TPU the flag routes straight to the jnp path (no kernel
        attempt, no exception noise) and the flush is correct."""
        from veneur_tpu.core.columnstore import HistoTable
        from veneur_tpu.samplers.parser import Parser

        t = HistoTable(256)
        t.pallas_flush = True
        parser = Parser()
        for pkt in (b"pf.lat:1|ms", b"pf.lat:2|ms", b"pf.lat:3|ms"):
            out = []
            parser.parse_metric_fast(pkt, out.append)
            t.add(out[0])
        res, export, touched, meta = t.snapshot_and_reset((0.5,))
        row = next(iter(t.rows.values()))
        assert touched[row]
        assert res["count"][row] == 3.0
        assert res["max"][row] == 3.0

    def test_kernel_failure_latches_jnp_fallback(self, monkeypatch):
        """A failing kernel must latch pallas off for the process and
        still deliver the flush through the jnp path — the contract
        config.py's pallas_tdigest_flush documents."""
        from veneur_tpu.core.columnstore import HistoTable
        from veneur_tpu.ops import batch_tdigest as btd
        from veneur_tpu.ops import pallas_tdigest as ptd
        from veneur_tpu.samplers.parser import Parser

        t = HistoTable(256)
        t.pallas_flush = True
        monkeypatch.setattr(t, "_use_pallas",
                            lambda: not ptd._State.failed)
        calls = []

        def boom(*a, **k):
            calls.append(1)
            raise RuntimeError("mosaic says no")

        monkeypatch.setattr(btd, "flush_quantiles_packed_pallas", boom)
        monkeypatch.setattr(btd, "flush_export_packed_pallas", boom)
        monkeypatch.setattr(ptd._State, "failed", False)
        parser = Parser()
        out = []
        parser.parse_metric_fast(b"lf.lat:7|ms", out.append)
        t.add(out[0])
        res, _, touched, _ = t.snapshot_and_reset((0.5,))
        row = next(iter(t.rows.values()))
        assert res["count"][row] == 1.0          # jnp fallback delivered
        assert calls == [1]
        assert ptd._State.failed is True         # latched
        # second flush: latch short-circuits, kernel never retried
        out2 = []
        parser.parse_metric_fast(b"lf.lat:9|ms", out2.append)
        t.add(out2[0])
        res2, _, _, _ = t.snapshot_and_reset((0.5,))
        assert res2["count"][row] == 1.0
        assert calls == [1]
