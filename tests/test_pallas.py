"""Parity tests for the pallas TPU kernels (run in interpret mode on the
CPU backend; the same kernels compile natively on TPU)."""

from __future__ import annotations

import numpy as np
import pytest

from veneur_tpu.ops import batch_hll, hll_ref, pallas_hll


class TestPallasHLLEstimate:
    def _random_regs(self, num_keys, seed=0, fill=0.3):
        rng = np.random.default_rng(seed)
        regs = np.zeros((num_keys, hll_ref.M), np.int8)
        mask = rng.random(regs.shape) < fill
        regs[mask] = rng.integers(1, 51, int(mask.sum()), dtype=np.int8)
        return regs

    def test_matches_jnp_path(self):
        regs = self._random_regs(pallas_hll.TK)
        want = np.asarray(batch_hll._estimate_jnp(regs))
        got = np.asarray(pallas_hll._estimate_pallas(regs, True))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_matches_scalar_reference(self):
        num_keys = pallas_hll.TK
        regs = np.zeros((num_keys, hll_ref.M), np.int8)
        rng = np.random.default_rng(7)
        cardinalities = [0, 1, 100, 5000]
        for row, n in enumerate(cardinalities):
            h = hll_ref.HLL()
            for i in range(n):
                h.insert(b"m%d-%d" % (row, i))
            regs[row] = h.regs
        got = np.asarray(pallas_hll._estimate_pallas(regs, True))
        for row, n in enumerate(cardinalities):
            want = hll_ref.estimate_from_registers(regs[row])
            assert got[row] == pytest.approx(want), (row, n)
            if n:
                assert got[row] == pytest.approx(n, rel=0.05), (row, n)
        # untouched rows estimate zero
        assert float(got[len(cardinalities)]) == 0.0

    def test_multi_tile(self):
        regs = self._random_regs(pallas_hll.TK * 3, seed=3, fill=0.05)
        want = np.asarray(batch_hll._estimate_jnp(regs))
        got = np.asarray(pallas_hll._estimate_pallas(regs, True))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_dispatch_falls_back_off_tpu(self):
        # on the CPU test backend estimate() must route to the jnp path
        regs = self._random_regs(pallas_hll.TK, seed=5, fill=0.1)
        want = np.asarray(batch_hll._estimate_jnp(regs))
        got = np.asarray(batch_hll.estimate(regs))
        np.testing.assert_allclose(got, want, rtol=1e-6)
