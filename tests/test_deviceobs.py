"""Device capacity & shard-balance observatory (the `deviceobs` marker).

The HBM ledger's contract is *conservation*: `total_bytes()` equals the
exact sum of every registered generation's nbytes — live generations
plus parked spares at quiescence, plus the in-flight snapshot mid
overlap — at every step of the lifecycle the column store can drive:

- generation swap under the overlapped (flush_async-shaped) flush,
  including the recycled-spare reuse on the following interval;
- a capacity resize (the grow drops and re-registers the live
  generation at the new rung);
- a prewarm-rung compile (the throwaway state is booked `prewarm` and
  dropped before the call returns);
- a live 2 -> 3 reshard (capture buffers ride `reshard_capture` into
  the snapshot and are dropped at cutover merge).

The shard-balance plane is pinned by a hot-key storm: rejection-sampled
names homed onto one shard drive `device.shard.skew` over threshold and
a `shard_skew` alert rule through idle -> pending -> firing with
trace-stamped alert_transition events. A `slow`-marked soak holds the
enabled-vs-disabled flush overhead under the same 2% bar as the
latency/query observatories.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax

from veneur_tpu.config import Config
from veneur_tpu.core.columnstore import ColumnStore
from veneur_tpu.core.deviceobs import (DeviceObservatory, HIST_ROWS,
                                       KERNEL_KINDS)
from veneur_tpu.core.flusher import (flush_columnstore_batch,
                                     readout_columnstore,
                                     swap_columnstore)
from veneur_tpu.core.server import Server
from veneur_tpu.samplers.metrics import HistogramAggregates
from veneur_tpu.samplers.parser import Parser
from veneur_tpu.sinks.channel import ChannelMetricSink

pytestmark = pytest.mark.deviceobs

PCTS = (0.5, 0.99)
AGGS = HistogramAggregates.from_names(
    ["min", "max", "median", "avg", "count", "sum"])


def corpus(round_no: int = 0):
    lines = []
    for i in range(8):
        lines.append(b"c.%d:%d|c|#env:t" % (i, i + 1 + round_no))
        lines.append(b"g.%d:%.2f|g" % (i, i * 1.5 + round_no))
        lines.append(b"t.%d:%.2f|ms" % (i, 10.0 + i + round_no))
        lines.append(b"s.%d:m%d|s" % (i, i))
        lines.append(b"ll.%d:%.2f|l" % (i, 3.0 + i + round_no))
    return lines


def _mk_store(**kw):
    kw.setdefault("counter_capacity", 64)
    kw.setdefault("gauge_capacity", 64)
    kw.setdefault("histo_capacity", 64)
    kw.setdefault("set_capacity", 32)
    kw.setdefault("llhist_capacity", 64)
    kw.setdefault("batch_cap", 128)
    return ColumnStore(**kw)


def _feed_store(store, lines):
    p = Parser()
    for line in lines:
        p.parse_metric_fast(line, store.process)
    store.apply_all_pending()


def mk_server(**kw):
    cfg = Config()
    cfg.interval = 3600.0
    cfg.hostname = "test"
    cfg.statsd_listen_addresses = []
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    cfg.ledger_strict = True
    for k, v in kw.items():
        if "." in k:
            ns, field = k.split(".", 1)
            setattr(getattr(cfg, ns), field, v)
        else:
            setattr(cfg, k, v)
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[obs]), obs


def _feed(server, lines, apply=True):
    for line in lines:
        server.handle_metric_packet(line)
    if apply:
        server.store.apply_all_pending()


def expected_bytes(store) -> int:
    """Ground truth the ledger must match at quiescence: the exact
    nbytes sum over every table's live device state plus its parked
    spare. (Mid-overlap the in-flight snapshot is extra — the overlap
    test accounts for it separately.)"""
    total = 0
    for _family, t in store.tables():
        state_of = getattr(t, "_devobs_state", None)
        if state_of is None:
            continue
        for tree in (state_of(), getattr(t, "_spare", None)):
            if tree is None:
                continue
            for leaf in jax.tree_util.tree_leaves(tree):
                total += int(getattr(leaf, "nbytes", 0))
    return total


def _inflight_bytes(obs) -> int:
    led = obs.ledger()
    return sum(states.get("inflight", 0) + states.get("reshard_capture", 0)
               for states in led["by_family"].values())


def _skewed_names(n_shards: int, shard: int, count: int, salt: str = "skew"):
    """Rejection-sample metric names whose digest64 homes onto `shard`
    under the (digest * n) >> 64 routing."""
    p = Parser()
    grabbed = []
    names, i = [], 0
    while len(names) < count:
        line = b"%s.%d:1|c" % (salt.encode(), i)
        i += 1
        del grabbed[:]
        p.parse_metric_fast(line, grabbed.append)
        d = grabbed[-1].digest64 & 0xFFFFFFFFFFFFFFFF
        if (d * n_shards) >> 64 == shard:
            names.append(line)
        assert i < 100_000, "rejection sampling runaway"
    return names


# -------------------------------------------------------------------------
# HBM ledger conservation
# -------------------------------------------------------------------------


class TestLedgerConservation:
    def test_attach_registers_exact(self):
        store = _mk_store()
        obs = DeviceObservatory()
        _feed_store(store, corpus())
        store.attach_deviceobs(obs)
        assert obs.total_bytes() == expected_bytes(store) > 0
        led = obs.ledger()
        assert led["live_bytes"] == led["total_bytes"]
        assert led["forecast_next_resize_bytes"] == 2 * led["live_bytes"]

    @pytest.mark.parametrize("is_local", [False, True])
    def test_swap_under_overlapped_flush(self, is_local):
        """The flush_async shape: swap on the interval thread, readout
        on a background thread while ingest continues. Mid-overlap the
        old generation is booked `inflight`; after the join/recycle it
        is the parked spare and the ledger is exact again — and the
        next interval's spare REUSE conserves bytes too."""
        store = _mk_store()
        obs = DeviceObservatory()
        store.attach_deviceobs(obs)
        _feed_store(store, corpus())
        swap = swap_columnstore(store, is_local, PCTS)
        # old generations in flight, fresh ones live: exact, with the
        # in-flight bytes on top of live+spare
        inflight = _inflight_bytes(obs)
        assert inflight > 0
        assert obs.total_bytes() == expected_bytes(store) + inflight

        result = {}

        def _readout():
            result["out"] = readout_columnstore(store, swap, is_local,
                                                AGGS)

        t = threading.Thread(target=_readout)
        t.start()
        _feed_store(store, corpus(round_no=7))
        t.join(30.0)
        assert not t.is_alive()
        # quiescent: snapshots recycled into spares, ledger exact
        assert _inflight_bytes(obs) == 0
        assert obs.total_bytes() == expected_bytes(store) > 0
        led = obs.ledger()
        spares = sum(s.get("spare", 0) for s in led["by_family"].values())
        assert spares > 0
        # interval 2 swaps INTO the recycled spares (retag, not fresh
        # registration) — still exact at quiescence
        flush_columnstore_batch(store, is_local, PCTS, AGGS)
        assert obs.total_bytes() == expected_bytes(store)

    def test_resize_grow_rebooks_live_generation(self):
        store = _mk_store(counter_capacity=64)
        obs = DeviceObservatory()
        store.attach_deviceobs(obs)
        before = obs.total_bytes()
        # mint past capacity to force the grow
        _feed_store(store, [b"rz.%d:1|c" % i for i in range(100)])
        assert store.counters.capacity > 64
        after = obs.total_bytes()
        assert after > before
        assert after == expected_bytes(store)
        # grown table survives a flush round with conservation intact
        flush_columnstore_batch(store, True, PCTS, AGGS)
        assert obs.total_bytes() == expected_bytes(store)

    def test_prewarm_rung_token_is_transient(self):
        store = _mk_store(counter_capacity=64)
        obs = DeviceObservatory()
        store.attach_deviceobs(obs)
        before = obs.total_bytes()
        assert store.counters.prewarm_rung(128, PCTS)
        # the throwaway rung state was booked `prewarm` and dropped
        assert obs.total_bytes() == before == expected_bytes(store)
        rep = obs.kernel_report()
        kinds = {(k["kind"], k["family"]) for k in rep["kernels"]}
        assert ("prewarm", "counter") in kinds
        assert rep["compiles"].get("counter", 0) >= 1

    def test_live_reshard_2_to_3_conserves(self, tmp_path):
        """The full migration: capture buffers ride `reshard_capture`
        through the WAL'd merge and are dropped at cutover; the
        re-topologized 3-shard generations register fresh. Exact at
        every quiescent point."""
        server, _obs = mk_server(**{"tpu.shards": 2},
                                 reshard_spool_dir=str(tmp_path / "wal"))
        try:
            obs = server.deviceobs
            assert obs is not None and obs.enabled
            _feed(server, corpus())
            assert obs.total_bytes() == expected_bytes(server.store)
            server.flush()
            assert obs.total_bytes() == expected_bytes(server.store)
            server.reshard.begin(shards=3, block=True)
            assert _inflight_bytes(obs) == 0
            assert obs.total_bytes() == expected_bytes(server.store) > 0
            # post-reshard interval still conserves
            _feed(server, corpus(round_no=3))
            server.flush()
            assert obs.total_bytes() == expected_bytes(server.store)
            bal = obs.shard_balance()
            assert bal is not None and bal["n_shards"] == 3
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_disabled_observatory_is_inert(self):
        server, _obs = mk_server(device_observatory=False)
        try:
            _feed(server, corpus())
            server.flush()
            assert server.deviceobs.total_bytes() == 0
            assert server.deviceobs.telemetry_rows() == []
            rep = server.device_report()
            assert rep["enabled"] is False
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()


# -------------------------------------------------------------------------
# Kernel registry & telemetry export
# -------------------------------------------------------------------------


class TestKernelRegistry:
    def test_flush_populates_dispatches_and_hists(self):
        store = _mk_store()
        obs = DeviceObservatory()
        store.attach_deviceobs(obs)
        _feed_store(store, corpus())
        flush_columnstore_batch(store, True, PCTS, AGGS)
        _feed_store(store, corpus(round_no=1))
        flush_columnstore_batch(store, True, PCTS, AGGS)
        rep = obs.kernel_report()
        kinds = {(k["kind"], k["family"]) for k in rep["kernels"]}
        assert ("apply", "counter") in kinds
        assert ("readout", "counter") in kinds
        assert ("reset", "counter") in kinds  # spare re-init on recycle
        # compiles are counted on the retrace paths: force a resize
        _feed_store(store, [b"kr.%d:1|c" % i for i in range(100)])
        rep = obs.kernel_report()
        assert rep["compiles"].get("counter", 0) >= 1
        timed = [k for k in rep["kernels"] if k.get("wall")]
        assert timed and all(k["wall"]["count"] >= 1 for k in timed)
        rows = {r[0] for r in obs.telemetry_rows()}
        assert {"device.mem.total_bytes", "device.mem.peak_bytes",
                "device.mem.generations", "device.mem.bytes",
                "device.kernel.dispatches",
                "device.compile.count"} <= rows
        # every exported hist row expands from the linted HIST_ROWS set
        hist_rows = {r for r in rows if ".kernel." in r
                     and r != "device.kernel.dispatches"}
        bases = {r.rsplit(".", 1)[0] for r in hist_rows}
        assert bases <= set(HIST_ROWS)
        assert set(KERNEL_KINDS) == {
            b.split(".")[-1][:-2] for b in HIST_ROWS}


# -------------------------------------------------------------------------
# Shard balance, skew alert, HTTP surface
# -------------------------------------------------------------------------


class TestShardBalance:
    def test_hot_key_storm_fires_shard_skew_rule(self):
        """Hot-key storm: names homed onto shard 0 drive the skew over
        threshold; a `shard_skew` rule walks idle -> pending -> firing
        with trace-stamped alert_transition events."""
        server, _obs = mk_server(**{"tpu.shards": 2})
        try:
            hot = _skewed_names(2, 0, 30)
            cold = _skewed_names(2, 1, 5, salt="cold")
            _feed(server, hot + cold)
            obs = server.deviceobs
            skew = obs.shard_skew()
            assert skew is not None and skew > 1.5
            server.alerts.configure([
                {"id": "skew", "kind": "shard_skew", "op": ">",
                 "threshold": 1.5, "for": "0.2s"},
            ])
            now = time.time()
            trs = server.alerts.evaluate_once(now=now)
            assert [(t["from_state"], t["to_state"]) for t in trs] == \
                [("idle", "pending")]
            assert server.alerts.evaluate_once(now=now + 0.1) == []
            trs = server.alerts.evaluate_once(now=now + 0.3)
            assert [(t["from_state"], t["to_state"]) for t in trs] == \
                [("pending", "firing")]
            rep = server.alerts.report()
            assert rep["rules"][0]["state"] == "firing"
            assert rep["rules"][0]["value"] == pytest.approx(skew,
                                                             rel=1e-6)
            events = server.telemetry.events.snapshot(
                kind="alert_transition")
            assert [e["to_state"] for e in events] == ["pending",
                                                       "firing"]
            assert all(e["rule"] == "skew" for e in events)
            assert all(e.get("trace_id") for e in events)
            # the gauge the rule watches is exported
            rows = {r[0]: r[2] for r in obs.telemetry_rows()}
            assert rows["device.shard.skew"] == pytest.approx(skew)
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_hot_shard_detection_and_reshard_plan(self):
        """All rows on one of four shards: skew 4.0, shard 0 flagged
        hot, and the planner recommends a rebalancing target priced in
        migration cells."""
        server, _obs = mk_server(**{"tpu.shards": 4})
        try:
            _feed(server, _skewed_names(4, 0, 24))
            bal = server.deviceobs.shard_balance()
            assert bal is not None
            assert bal["n_shards"] == 4
            assert sum(bal["rows_per_shard"]) == 24
            assert bal["rows_per_shard"][0] == 24
            assert bal["skew"] == pytest.approx(4.0)
            assert bal["hot_shards"] == [0]
            assert sum(bal["digest_occupancy"]) == 24
            plan = bal.get("reshard_plan")
            assert plan is not None
            assert plan["from_shards"] == 4
            assert plan["to_shards"] != 4
            assert plan["rows_moved"] >= 0
            assert plan["migration_cells"] is None or \
                plan["migration_cells"] >= 1
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()

    def test_unsharded_store_has_no_balance(self):
        store = _mk_store()
        obs = DeviceObservatory()
        store.attach_deviceobs(obs)
        _feed_store(store, corpus())
        assert obs.shard_balance() is None
        assert obs.shard_skew() is None

    def test_debug_device_http_surface(self):
        from veneur_tpu.core.httpapi import HTTPApi
        server, _obs = mk_server(**{"tpu.shards": 2})
        api = None
        try:
            _feed(server, corpus())
            server.flush()
            api = HTTPApi(server.config, server=server,
                          address="127.0.0.1:0")
            api.start()
            host, port = api.address
            with urllib.request.urlopen(
                    f"http://{host}:{port}/debug/device",
                    timeout=10) as r:
                assert r.status == 200
                body = json.loads(r.read())
            assert body["enabled"] is True
            assert body["ledger"]["total_bytes"] == \
                expected_bytes(server.store)
            assert body["kernels"]
            assert body["shard_balance"]["n_shards"] == 2
            assert "watermarks" in body
        finally:
            if api is not None:
                api.stop()
            server.config.flush_on_shutdown = False
            server.shutdown()


# -------------------------------------------------------------------------
# Overhead soak
# -------------------------------------------------------------------------


@pytest.mark.slow
class TestOverheadSoak:
    def test_observatory_overhead_bounded(self):
        """The acceptance soak: observatory enabled vs disabled, same
        corpus, same flush cadence (flush_async overlap shape) — flush
        wall and flush.critical_path_s p99 within 2% (plus the same
        absolute CI-jitter floor the query-plane soak uses)."""
        def soak(enabled):
            server, _obs = mk_server(flush_async=True,
                                     device_observatory=enabled)
            try:
                walls = []
                for k in range(2):  # warmup: compiles off both sides
                    _feed(server, corpus(round_no=k))
                    server.flush()
                for k in range(8):
                    _feed(server, corpus(round_no=10 + k))
                    t0 = time.perf_counter()
                    server.flush()
                    walls.append(time.perf_counter() - t0)
                crits = []
                for ri in server.telemetry.flushes.snapshot():
                    cp = ri.get("phases", {}).get("critical_path_s")
                    if cp is not None:
                        crits.append(float(cp))
                return walls, crits
            finally:
                server.config.flush_on_shutdown = False
                server.shutdown()

        base_walls, base_crits = soak(enabled=False)
        on_walls, on_crits = soak(enabled=True)
        base = float(np.mean(base_walls))
        loaded = float(np.mean(on_walls))
        assert loaded - base <= 0.02 * base + 0.25, \
            f"flush wall moved: off={base:.3f}s on={loaded:.3f}s"
        if base_crits and on_crits:
            bp99 = float(np.percentile(base_crits, 99))
            lp99 = float(np.percentile(on_crits, 99))
            assert lp99 <= bp99 * 1.02 + 0.25, \
                f"critical_path p99 moved: {bp99:.3f} -> {lp99:.3f}"
