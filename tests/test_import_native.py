"""Native MetricList import decoder (vnt_import_parse): must merge the
same state as the upb object path for every family, survive foreign
wire shapes (unknown fields, oversized digests, the retired `samples`
centroid field), and fall back cleanly on garbage."""

from __future__ import annotations

import numpy as np
import pytest

from veneur_tpu import native
from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.forward.client import _frame_v1
from veneur_tpu.forward.protos import forward_pb2, metric_pb2, tdigest_pb2
from veneur_tpu.forward.server import ImportServer, _MergeBuffer
from veneur_tpu.ops import batch_tdigest
from veneur_tpu.sinks.channel import ChannelMetricSink

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def mk_server():
    cfg = Config()
    cfg.interval = 3600
    cfg.hostname = "imp"
    cfg.statsd_listen_addresses = []
    cfg.tpu.histo_capacity = 1024
    cfg.apply_defaults()
    obs = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[obs]), obs


def digest_metric(name, means, weights, dmin=0.0, dmax=0.0, drecip=0.0,
                  tags=(), mtype=metric_pb2.Timer,
                  scope=metric_pb2.Mixed):
    d = tdigest_pb2.MergingDigestData(
        compression=batch_tdigest.COMPRESSION, min=dmin, max=dmax,
        reciprocalSum=drecip)
    for mean, w in zip(means, weights):
        d.main_centroids.add(mean=mean, weight=w)
    return metric_pb2.Metric(
        name=name, tags=list(tags), type=mtype, scope=scope,
        histogram=metric_pb2.HistogramValue(t_digest=d))


def body_of(metrics):
    return b"".join(_frame_v1(m.SerializeToString()) for m in metrics)


def flush_names_values(server, obs):
    server.flush()
    try:
        return {m.name: m.value for m in obs.wait_flush(timeout=2)}
    except Exception:  # a flush that emitted nothing
        return {}


class TestParityWithUpbPath:
    def test_all_families_merge_identically(self):
        rng = np.random.default_rng(5)
        metrics = []
        for i in range(40):
            metrics.append(metric_pb2.Metric(
                name=f"c{i}", tags=[f"t:{i % 4}"], type=metric_pb2.Counter,
                scope=metric_pb2.Global,
                counter=metric_pb2.CounterValue(value=i * 3)))
            metrics.append(metric_pb2.Metric(
                name=f"g{i}", type=metric_pb2.Gauge, scope=metric_pb2.Global,
                gauge=metric_pb2.GaugeValue(value=i * 0.5)))
            vals = rng.normal(50, 10, 30)
            metrics.append(digest_metric(
                f"h{i}", vals, rng.random(30) + 0.1,
                dmin=float(vals.min()), dmax=float(vals.max()),
                tags=(f"k:{i}",)))
        body = body_of(metrics)

        srv_a, obs_a = mk_server()
        imp_a = ImportServer(srv_a, "127.0.0.1:0")
        assert imp_a._merge_native(body) == (len(metrics), len(metrics))

        srv_b, obs_b = mk_server()
        imp_b = ImportServer(srv_b, "127.0.0.1:0")
        req = forward_pb2.MetricList.FromString(body)
        buf = _MergeBuffer(imp_b)
        for pbm in req.metrics:
            buf.add(pbm)
        buf.flush_all()

        got_a = flush_names_values(srv_a, obs_a)
        got_b = flush_names_values(srv_b, obs_b)
        assert set(got_a) == set(got_b)
        for name in got_b:
            assert got_a[name] == pytest.approx(got_b[name], rel=1e-4,
                                                abs=1e-4), name
        srv_a.shutdown()
        srv_b.shutdown()

    def test_sets_merge_identically(self):
        from veneur_tpu.forward import hllwire
        from veneur_tpu.ops import hll_ref

        rng = np.random.default_rng(9)
        regs = np.zeros(hll_ref.M, np.uint8)
        for _ in range(500):
            x = int(rng.integers(0, 2**63))
            idx, rho = hll_ref.pos_val(x)
            regs[idx] = max(regs[idx], rho)
        pbm = metric_pb2.Metric(
            name="s1", type=metric_pb2.Set, scope=metric_pb2.Global,
            set=metric_pb2.SetValue(hyper_log_log=hllwire.marshal(regs)))
        body = body_of([pbm])
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        assert imp._merge_native(body) == (1, 1)
        got = flush_names_values(srv, obs)
        assert got["s1"] == pytest.approx(500, rel=0.05)
        srv.shutdown()


class TestForeignShapes:
    def test_oversized_digest_rebuckets(self):
        # a foreign peer may send up to ~158 centroids; they must fold
        # onto the C-slot grid, preserving total weight
        rng = np.random.default_rng(3)
        n = batch_tdigest.C + 30
        vals = np.sort(rng.normal(100, 20, n))
        weights = rng.random(n) + 0.5
        # Global scope: mixed digests at the global tier deliberately
        # emit only percentiles (the local tier owns min/max/count)
        body = body_of([digest_metric("big", vals, weights,
                                      dmin=float(vals.min()),
                                      dmax=float(vals.max()),
                                      scope=metric_pb2.Global)])
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        assert imp._merge_native(body) == (1, 1)
        got = flush_names_values(srv, obs)
        assert got["big.count"] == pytest.approx(weights.sum(), rel=1e-3)
        assert got["big.min"] == pytest.approx(vals.min(), rel=1e-4)
        srv.shutdown()

    def test_unknown_fields_and_samples_skipped(self):
        pbm = digest_metric("x", [1.0, 2.0], [1.0, 1.0], dmin=1, dmax=2,
                            scope=metric_pb2.Global)
        raw = bytearray(pbm.SerializeToString())
        # append an unknown field 15 (varint) at the Metric level
        raw += bytes([15 << 3 | 0, 42])
        body = _frame_v1(bytes(raw))
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        assert imp._merge_native(body) == (1, 1)
        got = flush_names_values(srv, obs)
        assert got["x.count"] == pytest.approx(2.0)
        srv.shutdown()

    def test_unknown_type_enum_skipped(self):
        pbm = metric_pb2.Metric(
            name="odd", type=metric_pb2.Counter, scope=metric_pb2.Global,
            counter=metric_pb2.CounterValue(value=1))
        raw = bytearray(pbm.SerializeToString())
        # rewrite field 3 (type) to an unknown enum value 9
        body = body_of([pbm])
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        # hand-build: type=9 (open proto3 enum from a newer peer)
        alt = metric_pb2.Metric.FromString(pbm.SerializeToString())
        alt.type = 9
        body2 = body_of([alt])
        assert imp._merge_native(body2) == (1, 0)  # consumed, not merged
        got = flush_names_values(srv, obs)
        assert "odd" not in got
        srv.shutdown()

    def test_empty_digest_skipped(self):
        body = body_of([digest_metric("empty", [], [])])
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        assert imp._merge_native(body) == (1, 0)  # consumed, not merged
        got = flush_names_values(srv, obs)
        assert not any(k.startswith("empty") for k in got)
        srv.shutdown()

    def test_garbage_falls_back_to_none(self):
        srv, _obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        assert imp._merge_native(b"\xff\xff\xff\x07garbage") is None
        srv.shutdown()

    def test_truncated_nested_value_rejected(self):
        """A corrupt CounterValue (truncated varint) must reject the
        whole request — never merge a fabricated zero. The upb fallback
        then raises DecodeError to the sender, matching its contract."""
        srv, _obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        # Metric{name="x", counter=CounterValue<truncated varint>}
        bad = _frame_v1(b"\x0a\x01x\x2a\x02\x08\xff\x48\x02")
        assert imp._merge_native(bad) is None
        assert len(srv.store.counters.rows) == 0
        srv.shutdown()

    def test_zero_field_number_rejected(self):
        """A 0x00 byte mid-stream is invalid wire data (field number 0),
        not a clean end: metrics after it must not be silently dropped
        behind an OK ack."""
        good = metric_pb2.Metric(
            name="ok", type=metric_pb2.Counter, scope=metric_pb2.Global,
            counter=metric_pb2.CounterValue(value=1))
        body = body_of([good]) + b"\x00\x00\x00"
        srv, _obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        assert imp._merge_native(body) is None
        srv.shutdown()

    def test_wide_open_enum_not_aliased(self):
        """Open proto3 enums can exceed one byte; type=256 must not
        alias onto Counter through the key's uint8 truncation."""
        pbm = metric_pb2.Metric(
            name="wide", scope=metric_pb2.Global,
            counter=metric_pb2.CounterValue(value=5))
        pbm.type = 256
        body = body_of([pbm])
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        assert imp._merge_native(body) == (1, 0)  # consumed, not merged
        assert len(srv.store.counters.rows) == 0
        srv.shutdown()


class TestShardedStore:
    def test_native_import_into_sharded_tables(self):
        """tpu.shards routes the histo/set families through
        Sharded*Table; the native import's merge_batch calls must land
        there identically."""
        rng = np.random.default_rng(21)
        cfg = Config()
        cfg.interval = 3600
        cfg.hostname = "imp"
        cfg.statsd_listen_addresses = []
        cfg.tpu.histo_capacity = 512
        cfg.tpu.shards = 2
        cfg.apply_defaults()
        obs = ChannelMetricSink()
        srv = Server(cfg, extra_metric_sinks=[obs])
        imp = ImportServer(srv, "127.0.0.1:0")
        vals = rng.normal(10, 2, 40)
        body = body_of([
            digest_metric(f"sh{i}", vals, np.ones(40),
                          dmin=float(vals.min()), dmax=float(vals.max()),
                          scope=metric_pb2.Global)
            for i in range(32)])
        assert imp._merge_native(body) == (32, 32)
        got = flush_names_values(srv, obs)
        assert got["sh7.count"] == pytest.approx(40.0)
        assert got["sh7.min"] == pytest.approx(vals.min(), rel=1e-4)
        srv.shutdown()


class TestStubCache:
    def test_cache_hit_skips_rebuild(self):
        body = body_of([metric_pb2.Metric(
            name="cc", tags=["a:1"], type=metric_pb2.Counter,
            scope=metric_pb2.Global,
            counter=metric_pb2.CounterValue(value=2))])
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0")
        imp._merge_native(body)
        assert len(imp._stub_cache) == 1
        stub = next(iter(imp._stub_cache.values()))
        imp._merge_native(body)
        assert next(iter(imp._stub_cache.values())) is stub  # reused
        got = flush_names_values(srv, obs)
        assert got["cc"] == 4.0  # both merges landed
        srv.shutdown()

    def test_ignored_tags_filtered_once(self):
        from veneur_tpu.util.matcher import TagMatcher
        body = body_of([metric_pb2.Metric(
            name="ct", tags=["drop:me", "keep:yes"],
            type=metric_pb2.Counter, scope=metric_pb2.Global,
            counter=metric_pb2.CounterValue(value=1))])
        srv, obs = mk_server()
        imp = ImportServer(srv, "127.0.0.1:0",
                           ignored_tags=[TagMatcher(kind="prefix",
                                                    value="drop")])
        imp._merge_native(body)
        stub = next(iter(imp._stub_cache.values()))
        assert stub.tags == ["keep:yes"]
        srv.shutdown()
