"""Wire-compatibility regression tests against committed serialized
fixtures (the reference's regression_test.go + testdata/protobuf
pattern): refactors must keep parsing these exact bytes the same way."""

import os

import numpy as np

from veneur_tpu import protocol

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")


def fixture(name: str) -> bytes:
    with open(os.path.join(TESTDATA, name), "rb") as f:
        return f.read()


class TestSSFFixtures:
    def test_name_tag_migration(self):
        """A span serialized with an empty name and a "name" tag parses
        with the tag promoted to span.name and removed from tags
        (reference regression_test.go TestTagNameSetNameNotSet)."""
        span = protocol.parse_ssf(fixture("span_name_migration.pb"))
        assert span.name == "migrated.op"
        assert "name" not in span.tags
        assert span.tags["env"] == "prod"
        assert span.trace_id == 12345 and span.id == 678
        assert span.service == "fixture-svc"
        # zero sample rates normalize to 1.0
        assert span.metrics[0].sample_rate == 1.0
        assert span.metrics[0].value == 5.0

    def test_framed_stream_fixture(self):
        """Two framed spans committed as raw bytes decode in order and
        hit clean EOF."""
        import io
        stream = io.BytesIO(fixture("spans_framed.bin"))
        a = protocol.read_ssf(stream)
        b = protocol.read_ssf(stream)
        assert (a.id, a.name) == (2, "op.a")
        assert (b.id, b.name) == (3, "op.b")
        assert protocol.read_ssf(stream) is None  # clean EOF


class TestHLLWireFixture:
    def test_dense_v1_payload(self):
        """A committed axiomhq dense-v1 HLL payload unmarshals to the
        exact register values it was built from."""
        from veneur_tpu.forward import hllwire
        regs, p = hllwire.unmarshal(fixture("hll_dense_v1.bin"))
        assert p == 14
        want = np.zeros(16384, np.uint8)
        want[7] = 3
        want[100] = 12
        want[16383] = 1
        np.testing.assert_array_equal(regs, want)

    def test_roundtrip_stability(self):
        """marshal_dense(unmarshal(fixture)) reproduces the fixture
        byte-for-byte — the writer stays wire-stable too."""
        from veneur_tpu.forward import hllwire
        blob = fixture("hll_dense_v1.bin")
        regs, _ = hllwire.unmarshal(blob)
        assert hllwire.marshal_dense(regs.astype(np.uint8)) == blob


class TestMetricPBFixtures:
    def test_timer_digest_fixture_imports(self):
        """A committed forwardrpc Metric with a t-digest payload (what a
        Go local veneur would send) keys and imports identically across
        refactors."""
        from veneur_tpu.forward import convert
        from veneur_tpu.forward.protos import metric_pb2
        from veneur_tpu.samplers.metrics import MetricScope

        pbm = metric_pb2.Metric()
        pbm.ParseFromString(fixture("metricpb_timer.pb"))
        assert pbm.name == "fixture.timer"
        assert list(pbm.tags) == ["env:prod", "svc:api"]
        assert pbm.type == metric_pb2.Timer
        assert convert.import_scope(pbm) == MetricScope.MIXED
        key, h32, h64, tags = convert.metric_key_of_proto(pbm)
        assert key.name == "fixture.timer" and key.type == "timer"
        assert h32 != 0 and h64 != 0
        d = pbm.histogram.t_digest
        assert d.min == 1.5 and d.max == 42.0
        assert sum(c.weight for c in d.main_centroids) == 10.0
        assert d.reciprocalSum == 0.75

    def test_counter_fixture_scope(self):
        from veneur_tpu.forward import convert
        from veneur_tpu.forward.protos import metric_pb2
        from veneur_tpu.samplers.metrics import MetricScope

        pbm = metric_pb2.Metric()
        pbm.ParseFromString(fixture("metricpb_counter.pb"))
        assert pbm.counter.value == 99
        assert convert.import_scope(pbm) == MetricScope.GLOBAL_ONLY
