"""Wire-compatibility regression tests against committed serialized
fixtures (the reference's regression_test.go + testdata/protobuf
pattern): refactors must keep parsing these exact bytes the same way."""

import os

import numpy as np

from veneur_tpu import protocol

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")


def fixture(name: str) -> bytes:
    with open(os.path.join(TESTDATA, name), "rb") as f:
        return f.read()


class TestSSFFixtures:
    def test_name_tag_migration(self):
        """A span serialized with an empty name and a "name" tag parses
        with the tag promoted to span.name and removed from tags
        (reference regression_test.go TestTagNameSetNameNotSet)."""
        span = protocol.parse_ssf(fixture("span_name_migration.pb"))
        assert span.name == "migrated.op"
        assert "name" not in span.tags
        assert span.tags["env"] == "prod"
        assert span.trace_id == 12345 and span.id == 678
        assert span.service == "fixture-svc"
        # zero sample rates normalize to 1.0
        assert span.metrics[0].sample_rate == 1.0
        assert span.metrics[0].value == 5.0

    def test_framed_stream_fixture(self):
        """Two framed spans committed as raw bytes decode in order and
        hit clean EOF."""
        import io
        stream = io.BytesIO(fixture("spans_framed.bin"))
        a = protocol.read_ssf(stream)
        b = protocol.read_ssf(stream)
        assert (a.id, a.name) == (2, "op.a")
        assert (b.id, b.name) == (3, "op.b")
        assert protocol.read_ssf(stream) is None  # clean EOF


class TestHLLWireFixture:
    def test_dense_v1_payload(self):
        """A committed axiomhq dense-v1 HLL payload unmarshals to the
        exact register values it was built from."""
        from veneur_tpu.forward import hllwire
        regs, p = hllwire.unmarshal(fixture("hll_dense_v1.bin"))
        assert p == 14
        want = np.zeros(16384, np.uint8)
        want[7] = 3
        want[100] = 12
        want[16383] = 1
        np.testing.assert_array_equal(regs, want)

    def test_roundtrip_stability(self):
        """marshal_dense(unmarshal(fixture)) reproduces the fixture
        byte-for-byte — the writer stays wire-stable too."""
        from veneur_tpu.forward import hllwire
        blob = fixture("hll_dense_v1.bin")
        regs, _ = hllwire.unmarshal(blob)
        assert hllwire.marshal_dense(regs.astype(np.uint8)) == blob
