"""Test configuration: run JAX on a virtual 8-device CPU mesh so sharding
and collective paths are exercised without TPU hardware.

The environment's axon sitecustomize registers the TPU plugin at
interpreter startup and pins jax_platforms programmatically, so tests
must override both the environment and the jax config before any backend
initializes. Set VENEUR_TPU_TESTS=1 to opt in to running the suite on
real TPU hardware instead.
"""

import os

if os.environ.get("VENEUR_TPU_TESTS") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
