"""Test configuration: run JAX on a virtual 8-device CPU mesh so sharding
and collective paths are exercised without TPU hardware.

The environment's axon sitecustomize registers the TPU plugin at
interpreter startup and pins jax_platforms programmatically, so tests
must override both the environment and the jax config before any backend
initializes. Set VENEUR_TPU_TESTS=1 to opt in to running the suite on
real TPU hardware instead.
"""

import fnmatch
import os
import threading
import time

import pytest

if os.environ.get("VENEUR_TPU_TESTS") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


# -- thread-leak guard -----------------------------------------------------
#
# Supervisor/watchdog/monitor threads must never silently accumulate
# across tests: after each test, no NON-daemon thread may outlive the
# pre-test set. Daemon threads are exempt (the codebase's long-lived
# loops are daemonized by design and die with the process). The xfail
# list below exempts pre-existing offender patterns whose lifetime this
# codebase does not control — shrink it, never grow it: every thread
# the repo itself starts is named specifically (flush-ticker,
# pipeline-supervisor, overload-monitor, span-worker-N, http-api, ...)
# and is NOT exempt.
_THREAD_LEAK_XFAIL = (
    # grpc's executor workers and unnamed internal helpers reap on
    # their own schedule after server.stop() returns (grpc_wait_for_
    # shutdown is timing-dependent; it logs timeouts at interpreter
    # exit even on clean runs)
    "ThreadPoolExecutor-*",
    "Thread-*",
)

_LEAK_GRACE_S = 2.0


def _leaked_nondaemon(before):
    current = threading.current_thread()
    return [t for t in threading.enumerate()
            if t.is_alive() and not t.daemon
            and t is not current and t not in before]


@pytest.fixture(autouse=True)
def _thread_leak_guard():
    before = set(threading.enumerate())
    yield
    leaked = _leaked_nondaemon(before)
    deadline = time.monotonic() + _LEAK_GRACE_S
    while leaked and time.monotonic() < deadline:
        # shutdown paths join with bounded timeouts; give stragglers
        # one grace window before declaring a leak
        time.sleep(0.05)
        leaked = _leaked_nondaemon(before)
    offenders = [t.name for t in leaked
                 if not any(fnmatch.fnmatch(t.name, pat)
                            for pat in _THREAD_LEAK_XFAIL)]
    assert not offenders, (
        f"test leaked non-daemon thread(s): {sorted(offenders)} — "
        "join or daemonize them in the component's stop() path")
