"""Test configuration: run JAX on a virtual 8-device CPU mesh so sharding
and collective paths are exercised without TPU hardware.

The environment's axon sitecustomize pins JAX_PLATFORMS=axon (real TPU via
a tunnel) whenever PALLAS_AXON_POOL_IPS is set; tests override both unless
VENEUR_TPU_TESTS=1 explicitly opts in to running the suite on hardware.
"""

import os

if os.environ.get("VENEUR_TPU_TESTS") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
