"""Sink tests against local HTTP/UDP fakes — the reference's
httptest.Server pattern (e.g. sinks/datadog/datadog_test.go:496,
sinks/cortex/cortex_test.go:764)."""

import gzip
import json
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from veneur_tpu.samplers.metrics import InterMetric, MetricType
from veneur_tpu.ssf.protos import ssf_pb2
from veneur_tpu.util import http as vhttp


class CapturingHTTPServer:
    """Records every request (path, headers, body) and returns 200."""

    def __init__(self):
        outer = self
        self.requests = []
        self.event = threading.Event()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if self.headers.get("Content-Encoding") == "gzip":
                    body = gzip.decompress(body)
                outer.requests.append(
                    (self.path, dict(self.headers), body))
                outer.event.set()
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            do_GET = do_POST  # noqa: N815
            do_PUT = do_POST  # noqa: N815

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self):
        host, port = self.httpd.server_address
        return f"http://{host}:{port}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def fake():
    server = CapturingHTTPServer()
    yield server
    server.close()


def im(name="a.b.c", value=1.0, mtype=MetricType.COUNTER, tags=(),
       ts=1_700_000_000, hostname="h1", message=""):
    return InterMetric(name=name, timestamp=ts, value=value,
                       tags=list(tags), type=mtype, message=message,
                       hostname=hostname)


def make_span(trace_id=1, span_id=2, parent_id=0, name="op",
              service="svc", error=False, indicator=False, tags=None):
    s = ssf_pb2.SSFSpan()
    s.trace_id = trace_id
    s.id = span_id
    s.parent_id = parent_id
    s.name = name
    s.service = service
    s.error = error
    s.indicator = indicator
    s.start_timestamp = 1_700_000_000_000_000_000
    s.end_timestamp = 1_700_000_001_000_000_000
    for k, v in (tags or {}).items():
        s.tags[k] = v
    return s


class TestDatadog:
    def _sink(self, fake, **kw):
        from veneur_tpu.sinks.datadog import DatadogMetricSink
        return DatadogMetricSink("datadog", api_key="k", api_url=fake.url,
                                 hostname="dh", interval=10.0, **kw)

    def test_counter_rate_conversion_and_tags(self, fake):
        sink = self._sink(fake)
        sink.flush([im(value=50.0, tags=["a:b", "host:other", "device:sda"]),
                    im("g1", 7.0, MetricType.GAUGE)])
        path, _, body = fake.requests[0]
        assert path.startswith("/api/v1/series")
        assert "api_key=k" in path
        series = json.loads(body)["series"]
        counter = next(s for s in series if s["metric"] == "a.b.c")
        assert counter["type"] == "rate"
        assert counter["points"][0][1] == pytest.approx(5.0)  # 50/10s
        assert counter["host"] == "other"
        assert counter["device"] == "sda"
        assert "a:b" in counter["tags"]
        assert not any(t.startswith("host:") for t in counter["tags"])
        gauge = next(s for s in series if s["metric"] == "g1")
        assert gauge["type"] == "gauge"
        assert gauge["points"][0][1] == 7.0

    def test_chunking(self, fake):
        sink = self._sink(fake, flush_max_per_body=2)
        sink.flush([im(f"m{i}") for i in range(5)])
        assert len(fake.requests) == 3
        total = sum(len(json.loads(b)["series"]) for _, _, b in fake.requests)
        assert total == 5

    def test_metric_name_prefix_drops(self, fake):
        sink = self._sink(fake, metric_name_prefix_drops=["veneur."])
        sink.flush([im("veneur.flush.total"), im("app.reqs")])
        series = json.loads(fake.requests[0][2])["series"]
        assert [s["metric"] for s in series] == ["app.reqs"]

    def test_tag_exclusion_by_metric_prefix(self, fake):
        sink = self._sink(
            fake, excluded_tag_prefixes=["noisy"],
            exclude_tags_prefix_by_prefix_metric={"db.": ["shard"]})
        sink.flush([
            im("db.queries", tags=["shard:3", "env:prod", "noisy:x"]),
            im("web.hits", tags=["shard:3", "noisy:x"])])
        series = {s["metric"]: s for s in
                  json.loads(fake.requests[0][2])["series"]}
        assert series["db.queries"]["tags"] == ["env:prod"]
        assert series["web.hits"]["tags"] == ["shard:3"]

    def test_service_checks(self, fake):
        sink = self._sink(fake)
        sink.flush([im("check.up", 2.0, MetricType.STATUS,
                       message="oh no")])
        path, _, body = fake.requests[0]
        assert path.startswith("/api/v1/check_run")
        payload = json.loads(body)
        assert payload["check"] == "check.up"
        assert payload["status"] == 2
        assert payload["message"] == "oh no"

    def test_events(self, fake):
        from veneur_tpu.samplers.parser import Event
        sink = self._sink(fake)
        sink.flush_other_samples([Event(
            name="deploy", message="v2 shipped", timestamp=123,
            tags={"alert_type": "warning", "env": "prod"})])
        path, _, body = fake.requests[0]
        assert path.startswith("/intake")
        events = json.loads(body)["events"]["datadog"]
        assert events[0]["title"] == "deploy"
        assert events[0]["alert_type"] == "warning"
        assert "env:prod" in events[0]["tags"]

    def test_span_sink(self, fake):
        from veneur_tpu.sinks.datadog import DatadogSpanSink
        sink = DatadogSpanSink("datadog", trace_api_url=fake.url,
                               hostname="dh")
        sink.ingest(make_span(trace_id=5, span_id=6,
                              tags={"resource": "GET /"}))
        sink.ingest(make_span(trace_id=5, span_id=7, parent_id=6))
        sink.ingest(make_span(trace_id=0))  # no trace id -> dropped
        sink.flush()
        _, _, body = fake.requests[0]
        traces = json.loads(body)
        assert len(traces) == 1
        assert len(traces[0]) == 2
        assert traces[0][0]["resource"] == "GET /"
        # second flush with nothing buffered: no POST
        sink.flush()
        assert len(fake.requests) == 1


class TestCortex:
    def test_remote_write_roundtrip(self, fake):
        from veneur_tpu.sinks.cortex import (
            CortexMetricSink, decode_write_request)
        sink = CortexMetricSink("cortex", url=fake.url, hostname="ch",
                                auth_token="tok")
        sink.flush([im("http.requests", 3.5, MetricType.GAUGE,
                       tags=["region:us", "bad-label:x"])])
        _, headers, body = fake.requests[0]
        assert headers["Content-Encoding"] == "snappy"
        assert headers["X-Prometheus-Remote-Write-Version"] == "0.1.0"
        assert headers["Authorization"] == "Bearer tok"
        series = decode_write_request(vhttp.snappy_decode(body))
        labels, value, ts = series[0]
        assert labels["__name__"] == "http.requests".replace(".", "_") \
            or labels["__name__"] == "http.requests"
        assert labels["region"] == "us"
        assert labels["bad_label"] == "x"
        assert labels["host"] == "h1"  # metric hostname wins
        assert value == 3.5
        assert ts == 1_700_000_000_000

    def test_name_sanitization(self):
        from veneur_tpu.sinks.cortex import sanitize_label, sanitize_name
        assert sanitize_name("a.b-c/d") == "a_b_c_d"
        assert sanitize_name("9lives") == "_9lives"
        assert sanitize_name("ok:name_1") == "ok:name_1"
        assert sanitize_label("a:b") == "a_b"

    def test_batching(self, fake):
        from veneur_tpu.sinks.cortex import CortexMetricSink
        sink = CortexMetricSink("cortex", url=fake.url, hostname="ch",
                                batch_write_size=2)
        sink.flush([im(f"m{i}", i, MetricType.GAUGE) for i in range(5)])
        assert len(fake.requests) == 3


class TestPrometheus:
    def test_exposition(self):
        from veneur_tpu.sinks.prometheus import render_exposition
        text = render_exposition([
            im("req.count", 5, MetricType.COUNTER, tags=["code:200"]),
            im("check", 0, MetricType.STATUS)])
        assert 'req_count{code="200"} 5' in text
        assert "check" not in text

    def test_expose_endpoint_and_repeater(self):
        from veneur_tpu.sinks.prometheus import PrometheusMetricSink
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5.0)
        port = recv.getsockname()[1]
        sink = PrometheusMetricSink(
            "prometheus", repeater_address=f"127.0.0.1:{port}",
            expose_address="127.0.0.1:0")
        sink.start(None)
        try:
            sink.flush([im("up", 1, MetricType.GAUGE, tags=["a:b"])])
            data, _ = recv.recvfrom(65536)
            assert data == b"up:1|g|#a:b"
            status, body = vhttp.get(
                f"http://127.0.0.1:{sink.expose_port}/metrics")
            assert status == 200
            assert b"up{" in body
        finally:
            sink.stop()
            recv.close()


class TestSignalFx:
    def test_datapoints_and_token_routing(self, fake):
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink(
            "signalfx", api_key="default-tok", endpoint=fake.url,
            hostname="sh", vary_key_by="customer",
            per_tag_tokens={"acme": "acme-tok"})
        sink.flush([
            im("c1", 2, MetricType.COUNTER, tags=["customer:acme"]),
            im("g1", 3, MetricType.GAUGE)])
        assert len(fake.requests) == 2
        # urllib normalizes header casing; match case-insensitively
        by_token = {
            next(v for k, v in h.items() if k.lower() == "x-sf-token"):
            json.loads(b) for _, h, b in fake.requests}
        assert by_token["acme-tok"]["counter"][0]["metric"] == "c1"
        assert by_token["acme-tok"]["counter"][0]["dimensions"][
            "customer"] == "acme"
        assert by_token["default-tok"]["gauge"][0]["metric"] == "g1"
        assert by_token["default-tok"]["gauge"][0]["dimensions"][
            "host"] == "h1"  # metric hostname wins over sink hostname

    def test_status_checks_emit_as_gauges(self, fake):
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink("signalfx", api_key="t",
                                  endpoint=fake.url, hostname="sh")
        sink.flush([im("svc.up", 2, MetricType.STATUS)])
        payload = json.loads(fake.requests[0][2])
        assert payload["gauge"][0]["metric"] == "svc.up"
        assert payload["gauge"][0]["value"] == 2

    def test_drop_host_with_tag_key(self, fake):
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink(
            "signalfx", api_key="t", endpoint=fake.url, hostname="sh",
            drop_host_with_tag_key="multihost")
        sink.flush([
            im("c1", 1, MetricType.COUNTER, tags=["multihost:yes"]),
            im("c2", 1, MetricType.COUNTER),
            im("g1", 1, MetricType.GAUGE, tags=["multihost:yes"])])
        payload = json.loads(fake.requests[0][2])
        dims = {p["metric"]: p["dimensions"]
                for kind in payload.values() for p in kind}
        assert "host" not in dims["c1"]  # counter with the tag: dropped
        assert dims["c2"]["host"] == "h1"  # counter without: kept
        assert dims["g1"]["host"] == "h1"  # gauges never drop

    def test_event_flush(self, fake):
        from veneur_tpu.samplers.parser import Event
        from veneur_tpu.samplers.parser import EVENT_IDENTIFIER_KEY
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink("signalfx", api_key="t",
                                  endpoint=fake.url, hostname="sh")
        ev = Event(name="deploy", message="%%% \nrolled out\n %%%",
                   timestamp=1000,
                   tags={EVENT_IDENTIFIER_KEY: "", "env": "prod"})
        not_event = Event(name="no", message="x", timestamp=1,
                          tags={"env": "prod"})
        sink.flush_other_samples([ev, not_event])
        path, _, body = fake.requests[0]
        assert path == "/v2/event"
        events = json.loads(body)
        assert len(events) == 1  # non-event sample ignored
        assert events[0]["eventType"] == "deploy"
        assert events[0]["properties"]["description"] == "rolled out"
        assert events[0]["dimensions"]["env"] == "prod"
        assert EVENT_IDENTIFIER_KEY not in events[0]["dimensions"]

    def test_event_truncation(self, fake):
        from veneur_tpu.samplers.parser import Event
        from veneur_tpu.samplers.parser import EVENT_IDENTIFIER_KEY
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink("signalfx", api_key="t",
                                  endpoint=fake.url, hostname="sh")
        ev = Event(name="n" * 400, message="m" * 400, timestamp=1,
                   tags={EVENT_IDENTIFIER_KEY: ""})
        sink.flush_other_samples([ev])
        events = json.loads(fake.requests[0][2])
        assert len(events[0]["eventType"]) == 256
        assert len(events[0]["properties"]["description"]) == 256

    def test_flush_max_per_body_chunks(self, fake):
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink("signalfx", api_key="t",
                                  endpoint=fake.url, hostname="sh",
                                  flush_max_per_body=3)
        sink.flush([im(f"m{i}", i, MetricType.GAUGE) for i in range(8)])
        assert len(fake.requests) == 3  # ceil(8/3)
        total = sum(len(json.loads(b).get("gauge", []))
                    for _, _, b in fake.requests)
        assert total == 8


class TestKafka:
    def test_metric_sink(self):
        from veneur_tpu.sinks.kafka import InMemoryProducer, KafkaMetricSink
        producer = InMemoryProducer()
        sink = KafkaMetricSink("kafka", producer, metric_topic="metrics")
        sink.flush([im("k1", 9, tags=["x:y"])])
        topic, key, value = producer.messages[0]
        assert topic == "metrics"
        assert key == b"k1"
        decoded = json.loads(value)
        assert decoded["value"] == 9
        assert decoded["tags"] == ["x:y"]

    def test_span_sink_sampling(self):
        from veneur_tpu.sinks.kafka import InMemoryProducer, KafkaSpanSink
        producer = InMemoryProducer()
        sink = KafkaSpanSink("kafka", producer, span_topic="spans",
                             encoding="json", sample_rate_percent=50.0)
        for tid in range(1, 101):
            sink.ingest(make_span(trace_id=tid))
        sink.flush()
        kept = len(producer.messages)
        assert 0 < kept < 100  # deterministic by trace id, roughly half
        # identical ingest keeps/drops the same traces
        decoded = json.loads(producer.messages[0][2])
        assert "trace_id" in decoded

    def test_span_protobuf_encoding(self):
        from veneur_tpu.sinks.kafka import InMemoryProducer, KafkaSpanSink
        producer = InMemoryProducer()
        sink = KafkaSpanSink("kafka", producer, span_topic="spans")
        sink.ingest(make_span(trace_id=42))
        parsed = ssf_pb2.SSFSpan()
        parsed.ParseFromString(producer.messages[0][2])
        assert parsed.trace_id == 42


class TestS3:
    def test_tsv_upload(self):
        from veneur_tpu.sinks.s3 import InMemoryUploader, S3MetricSink
        uploader = InMemoryUploader()
        sink = S3MetricSink("s3", uploader, bucket="b", hostname="s3h",
                            interval=10.0)
        sink.flush([im("s.m", 4.5, MetricType.GAUGE, tags=["t:1"])])
        bucket, key, body = uploader.objects[0]
        assert bucket == "b"
        assert key.startswith("s3h/")
        row = gzip.decompress(body).decode().strip().split("\t")
        assert row[0] == "s.m"
        assert row[1] == "t:1"
        assert row[2] == "gauge"
        assert float(row[5]) == 4.5


class TestCloudWatch:
    def test_put_metric_data(self, fake):
        from veneur_tpu.sinks.cloudwatch import CloudWatchMetricSink
        sink = CloudWatchMetricSink("cloudwatch", endpoint=fake.url + "/",
                                    namespace="ns")
        sink.flush([im("cw.m", 2.5, MetricType.GAUGE, tags=["az:us-1a"])])
        _, _, body = fake.requests[0]
        params = dict(urllib.parse.parse_qsl(body.decode()))
        assert params["Action"] == "PutMetricData"
        assert params["Namespace"] == "ns"
        assert params["MetricData.member.1.MetricName"] == "cw.m"
        assert float(params["MetricData.member.1.Value"]) == 2.5
        assert params["MetricData.member.1.Dimensions.member.1.Name"] == "az"

    def test_chunking_and_signing(self, fake):
        from veneur_tpu.sinks.cloudwatch import CloudWatchMetricSink
        sink = CloudWatchMetricSink(
            "cloudwatch", endpoint=fake.url + "/", namespace="ns",
            region="us-east-1", credentials=("AKID", "SECRET"))
        sink.flush([im(f"m{i}") for i in range(25)])
        assert len(fake.requests) == 2
        _, headers, _ = fake.requests[0]
        assert headers["Authorization"].startswith(
            "AWS4-HMAC-SHA256 Credential=AKID/")
        assert "X-Amz-Date" in headers


class TestSplunk:
    def test_hec_events(self, fake):
        from veneur_tpu.sinks.splunk import SplunkSpanSink
        sink = SplunkSpanSink("splunk", hec_address=fake.url, token="tok",
                              hostname="sph", index="idx")
        sink.ingest(make_span(trace_id=10, tags={"k": "v"}))
        sink.ingest(make_span(trace_id=11, error=True))
        sink.flush()
        _, headers, body = fake.requests[0]
        assert headers["Authorization"] == "Splunk tok"
        events = [json.loads(line) for line in body.splitlines()]
        assert len(events) == 2
        assert events[0]["index"] == "idx"
        assert events[0]["event"]["tags"] == {"k": "v"}
        assert events[1]["event"]["error"] is True

    def test_sampling_keeps_indicators(self, fake):
        from veneur_tpu.sinks.splunk import SplunkSpanSink
        sink = SplunkSpanSink("splunk", hec_address=fake.url, token="t",
                              hostname="h", sample_rate=10)
        for tid in range(1, 101):
            sink.ingest(make_span(trace_id=tid))
        sink.ingest(make_span(trace_id=7, indicator=True))
        sink.flush()
        _, _, body = fake.requests[0]
        events = [json.loads(line) for line in body.splitlines()]
        # 10 sampled (trace_id % 10 == 0) + 1 indicator
        assert len(events) == 11


class TestXRay:
    def test_segments_over_udp(self):
        from veneur_tpu.sinks.xray import XRaySpanSink
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5.0)
        port = recv.getsockname()[1]
        sink = XRaySpanSink("xray", daemon_address=f"127.0.0.1:{port}",
                            annotation_tags=["env"])
        sink.start(None)
        try:
            sink.ingest(make_span(trace_id=99, span_id=100, parent_id=1,
                                  tags={"env": "prod", "other": "x"}))
            data, _ = recv.recvfrom(65536)
            header, payload = data.split(b"\n", 1)
            assert json.loads(header)["format"] == "json"
            seg = json.loads(payload)
            assert seg["trace_id"].startswith("1-")
            assert seg["annotations"] == {"env": "prod"}
            assert seg["type"] == "subsegment"
            assert sink.spans_handled == 1
        finally:
            sink.stop()
            recv.close()


class TestFalconerLightstepNewrelic:
    def test_falconer_sender(self):
        from veneur_tpu.sinks.falconer import FalconerSpanSink
        sent = []
        sink = FalconerSpanSink("falconer", sender=sent.append)
        sink.ingest(make_span(trace_id=3))
        assert sink.spans_handled == 1
        assert sent[0].trace_id == 3

    def test_lightstep(self, fake):
        from veneur_tpu.sinks.lightstep import LightStepSpanSink
        sink = LightStepSpanSink("lightstep", access_token="at",
                                 collector_url=fake.url, num_clients=2)
        sink.ingest(make_span(trace_id=1))
        sink.ingest(make_span(trace_id=2))
        sink.flush()
        assert len(fake.requests) == 2  # one OTLP request per stripe
        path, headers, body = fake.requests[0]
        assert path.endswith("/v1/traces")
        lower = {k.lower(): v for k, v in headers.items()}
        assert lower["lightstep-access-token"] == "at"
        payload = json.loads(body)
        spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert len(spans) == 1

    def test_newrelic_metrics(self, fake):
        from veneur_tpu.sinks.newrelic import NewRelicMetricSink
        sink = NewRelicMetricSink(
            "newrelic", insert_key="ik", hostname="nh", interval=10.0,
            metric_url=fake.url + "/metric/v1")
        sink.flush([im("nr.c", 5, MetricType.COUNTER),
                    im("nr.g", 6, MetricType.GAUGE)])
        _, headers, body = fake.requests[0]
        assert headers["Api-Key"] == "ik"
        metrics = json.loads(body)[0]["metrics"]
        count = next(m for m in metrics if m["name"] == "nr.c")
        assert count["type"] == "count"
        assert count["interval.ms"] == 10_000
        gauge = next(m for m in metrics if m["name"] == "nr.g")
        assert gauge["type"] == "gauge"

    def test_newrelic_spans(self, fake):
        from veneur_tpu.sinks.newrelic import NewRelicSpanSink
        sink = NewRelicSpanSink("newrelic", insert_key="ik",
                                trace_url=fake.url + "/trace/v1")
        sink.ingest(make_span(trace_id=8, span_id=9, parent_id=4))
        sink.flush()
        _, _, body = fake.requests[0]
        spans = json.loads(body)[0]["spans"]
        assert spans[0]["trace.id"] == "8"
        assert spans[0]["attributes"]["parent.id"] == "4"
        assert spans[0]["attributes"]["duration.ms"] == pytest.approx(1000.0)


class TestRegistry:
    def test_all_kinds_registered(self):
        from veneur_tpu import sinks as sinks_mod
        sinks_mod.register_builtin_sinks()
        for kind in ("datadog", "signalfx", "cortex", "kafka", "s3",
                     "cloudwatch", "prometheus", "newrelic", "blackhole",
                     "debug", "localfile", "channel"):
            assert kind in sinks_mod.MetricSinkTypes, kind
        for kind in ("datadog", "kafka", "splunk", "xray", "falconer",
                     "lightstep", "newrelic"):
            assert kind in sinks_mod.SpanSinkTypes, kind


class TestDatadogSpanDepth:
    """Reference datadog.go:453-660 span-path semantics."""

    def test_ring_overflow_accounting(self):
        from veneur_tpu.sinks.datadog import DatadogSpanSink
        sink = DatadogSpanSink("datadog", trace_api_url="http://x",
                               hostname="dh", buffer_size=4)
        for i in range(7):
            sink.ingest(make_span(trace_id=1, span_id=i + 1))
        assert len(sink.buffer) == 4  # oldest overwritten, never blocks
        assert sink.overwritten_total == 3
        ids = [s.id for s in sink.buffer]
        assert ids == [4, 5, 6, 7]

    def test_dd_span_shape(self, fake):
        from veneur_tpu.sinks.datadog import DatadogSpanSink
        sink = DatadogSpanSink("datadog", trace_api_url=fake.url,
                               hostname="dh")
        root = make_span(trace_id=9, span_id=1, parent_id=-1,
                         tags={"resource": "GET /x", "env": "t"})
        root.error = True
        sink.ingest(root)
        child = make_span(trace_id=9, span_id=2, parent_id=1)
        child.name = ""
        sink.ingest(child)
        sink.flush()
        path, headers, body = fake.requests[0]
        assert path == "/v0.3/traces"
        # the traces endpoint takes an uncompressed PUT
        assert headers.get("Content-Encoding") is None
        traces = json.loads(body)
        assert len(traces) == 1
        by_id = {s["span_id"]: s for s in traces[0]}
        assert by_id[1]["parent_id"] == 0        # root clamps to 0
        assert by_id[1]["resource"] == "GET /x"  # promoted out of meta
        assert "resource" not in by_id[1]["meta"]
        assert by_id[1]["error"] == 2
        assert by_id[1]["type"] == "web"
        assert by_id[2]["name"] == "unknown"
        assert by_id[2]["resource"] == "unknown"

    def test_flush_self_metrics_per_service(self, fake):
        from veneur_tpu.sinks.datadog import DatadogSpanSink
        calls = []

        class FakeStatsd:
            def count(self, name, value, tags=None):
                calls.append((name, value, tuple(tags or ())))

            def gauge(self, name, value, tags=None):
                calls.append((name, value, tuple(tags or ())))

        class FakeServer:
            statsd = FakeStatsd()

        sink = DatadogSpanSink("datadog", trace_api_url=fake.url,
                               hostname="dh")
        sink.start(FakeServer())
        s1 = make_span(trace_id=1, span_id=1)
        s1.service = "api"
        s2 = make_span(trace_id=2, span_id=2)
        s2.service = "api"
        s3 = make_span(trace_id=3, span_id=3)
        s3.service = "db"
        for s in (s1, s2, s3):
            sink.ingest(s)
        sink.flush()
        flushed = {c for c in calls if c[0] == "sink.spans_flushed_total"}
        assert ("sink.spans_flushed_total", 2,
                ("sink:datadog", "service:api")) in flushed
        assert ("sink.spans_flushed_total", 1,
                ("sink:datadog", "service:db")) in flushed
        assert any(c[0] == "sink.span_flush_total_duration_ns"
                   for c in calls)


class TestKafkaBackpressure:
    def test_span_buffer_bound_drops_and_counts(self):
        from veneur_tpu.sinks.kafka import InMemoryProducer, KafkaSpanSink
        prod = InMemoryProducer()
        sink = KafkaSpanSink("kafka", prod, span_topic="spans",
                             max_buffered=3)
        for i in range(5):
            sink.ingest(make_span(trace_id=i + 1, span_id=1))
        assert len(prod.messages) == 3
        assert sink.dropped_total == 2
        sink.flush()  # resets the per-interval bound
        sink.ingest(make_span(trace_id=9, span_id=1))
        assert len(prod.messages) == 4


class TestFalconerDepth:
    def test_validates_and_counts(self):
        from veneur_tpu.sinks.falconer import FalconerSpanSink
        sent = []
        sink = FalconerSpanSink("falconer", sender=sent.append)
        sink.ingest(make_span(trace_id=1, span_id=2))
        sink.ingest(make_span(trace_id=0, span_id=2))  # invalid: no trace
        sink.ingest(make_span(trace_id=3, span_id=0))  # invalid: no id
        assert len(sent) == 1
        assert sink.spans_handled == 1

        def boom(span):
            raise RuntimeError("conn reset")
        sink.sender = boom
        sink.ingest(make_span(trace_id=5, span_id=6))
        assert sink.errors == 1

    def test_grpc_route_parity(self):
        from veneur_tpu.sinks.falconer import GrpcSpanSender
        # reference generated client invokes /falconer.SpanSink/SendSpan
        # (sinks/falconer/grpc_sink.pb.go:108)
        assert GrpcSpanSender.METHOD == "/falconer.SpanSink/SendSpan"


class TestNewRelicBackpressure:
    def test_span_buffer_bound(self):
        from veneur_tpu.sinks.newrelic import NewRelicSpanSink
        sink = NewRelicSpanSink("nr", insert_key="k",
                                trace_url="http://x", max_buffered=2)
        for i in range(4):
            sink.ingest(make_span(trace_id=i + 1, span_id=1))
        assert len(sink._spans) == 2
        assert sink.dropped_total == 2


class TestSpanFlushSelfMetrics:
    """Uniform span-sink flush self-metrics (reference sinks.go:58-67)."""

    class FakeStatsd:
        def __init__(self):
            self.calls = []

        def count(self, name, value, tags=None):
            self.calls.append((name, value, tuple(tags or ())))

        def gauge(self, name, value, tags=None):
            self.calls.append((name, value, tuple(tags or ())))

    class FakeServer:
        def __init__(self, statsd):
            self.statsd = statsd

    def test_splunk_emits_flush_keys(self, fake):
        from veneur_tpu.sinks.splunk import SplunkSpanSink
        statsd = self.FakeStatsd()
        sink = SplunkSpanSink("splunk", hec_address=fake.url, token="t",
                              hostname="h", max_buffer=2)
        sink.start(self.FakeServer(statsd))
        for i in range(4):
            sink.ingest(make_span(trace_id=i + 1, span_id=1))
        sink.flush()
        names = {c[0] for c in statsd.calls}
        assert "sink.spans_flushed_total" in names
        assert "sink.spans_dropped_total" in names
        assert "sink.span_flush_total_duration_ns" in names
        by = {c[0]: c for c in statsd.calls}
        assert by["sink.spans_flushed_total"][1] == 2
        assert by["sink.spans_dropped_total"][1] == 2
        assert by["sink.spans_flushed_total"][2] == ("sink:splunk",)

    def test_lightstep_emits_flush_keys(self, fake):
        from veneur_tpu.sinks.lightstep import LightStepSpanSink
        statsd = self.FakeStatsd()
        sink = LightStepSpanSink("lightstep", collector_url=fake.url,
                                 access_token="t")
        sink.start(self.FakeServer(statsd))
        sink.ingest(make_span(trace_id=1, span_id=1))
        sink.ingest(make_span(trace_id=2, span_id=2))
        sink.flush()
        by = {c[0]: c for c in statsd.calls}
        assert by["sink.spans_flushed_total"][1] == 2


class TestXRayTraceId:
    def test_same_trace_same_id_across_seconds(self):
        """Without root_start_timestamp, spans of one trace agree via the
        256 s bucket of their own starts (reference xray.go:290-306 —
        probabilistic: only spans within one bucket agree, so the test
        places both starts inside a single bucket)."""
        from veneur_tpu.sinks.xray import xray_trace_id
        a = make_span(trace_id=77, span_id=1)
        b = make_span(trace_id=77, span_id=2)
        base = 1_700_000_000 * 10**9  # 256-aligned epoch: bucket start
        a.start_timestamp = base
        b.start_timestamp = base + 5 * 10**9  # 5 s later, same bucket
        assert xray_trace_id(a) == xray_trace_id(b)
        # straddling a bucket boundary splits (documented reference
        # behavior); root_start_timestamp is the robust path
        c = make_span(trace_id=77, span_id=3)
        c.start_timestamp = base - 10**9
        assert xray_trace_id(c) != xray_trace_id(a)

    def test_root_timestamp_preferred(self):
        from veneur_tpu.sinks.xray import xray_trace_id
        s = make_span(trace_id=5, span_id=1)
        s.start_timestamp = 1_700_000_999 * 10**9
        s.root_start_timestamp = 1_700_000_000 * 10**9
        assert xray_trace_id(s).split("-")[1] == f"{1_700_000_000:08x}"


class TestSignalFxRoutingExtras:
    def test_metric_tag_prefix_drops(self, fake):
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink(
            "signalfx", api_key="t", endpoint=fake.url, hostname="sh",
            metric_tag_prefix_drops=["internal."])
        sink.flush([
            im("kept", 1, MetricType.GAUGE, tags=["env:prod"]),
            im("dropped", 1, MetricType.GAUGE,
               tags=["internal.debug:yes"])])
        payload = json.loads(fake.requests[0][2])
        names = {p["metric"] for kind in payload.values() for p in kind}
        assert names == {"kept"}
        assert sink.skipped_total == 1

    def test_preferred_vary_key_beats_vary_key(self, fake):
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink(
            "signalfx", api_key="default-tok", endpoint=fake.url,
            hostname="sh", vary_key_by="customer",
            preferred_vary_key_by="team",
            per_tag_tokens={"acme": "acme-tok", "infra": "infra-tok"})
        sink.flush([im("m1", 1, MetricType.GAUGE,
                       tags=["customer:acme", "team:infra"])])
        tok = next(v for k, v in fake.requests[0][1].items()
                   if k.lower() == "x-sf-token")
        assert tok == "infra-tok"

    def test_excluded_tag_still_routes_token(self, fake):
        """Token selection sees the full dimension set; excluded tags are
        removed only afterwards (signalfx.go:534-564)."""
        from veneur_tpu.sinks.signalfx import SignalFxMetricSink
        sink = SignalFxMetricSink(
            "signalfx", api_key="default-tok", endpoint=fake.url,
            hostname="sh", vary_key_by="customer",
            excluded_tags=["customer"],
            per_tag_tokens={"acme": "acme-tok"})
        sink.flush([im("m1", 1, MetricType.GAUGE, tags=["customer:acme"])])
        _, headers, body = fake.requests[0]
        tok = next(v for k, v in headers.items()
                   if k.lower() == "x-sf-token")
        assert tok == "acme-tok"
        dims = json.loads(body)["gauge"][0]["dimensions"]
        assert "customer" not in dims

    def test_fetch_api_keys_paginates(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        from veneur_tpu.sinks.signalfx import fetch_api_keys

        pages = {
            0: [{"name": "a", "secret": "s-a"},
                {"name": "b", "secret": "s-b"}],
            200: [{"name": "c", "secret": "s-c"}],
            400: [],
        }
        seen_tokens = []

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                q = parse_qs(urlparse(self.path).query)
                seen_tokens.append(self.headers.get("X-SF-Token"))
                body = json.dumps(
                    {"results": pages[int(q["offset"][0])]}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}"
            tokens = fetch_api_keys(url, "api-tok")
            assert tokens == {"a": "s-a", "b": "s-b", "c": "s-c"}
            assert set(seen_tokens) == {"api-tok"}
        finally:
            httpd.shutdown()

    def test_dynamic_keys_require_refresh_period(self):
        from veneur_tpu.config import Config, SinkConfig
        from veneur_tpu.sinks import MetricSinkTypes, register_builtin_sinks
        register_builtin_sinks()
        cfg = Config()
        cfg.apply_defaults()
        sc = SinkConfig(kind="signalfx", name="sfx", config={
            "dynamic_per_tag_api_keys_enable": True})
        with pytest.raises(ValueError, match="refresh period is unset"):
            MetricSinkTypes["signalfx"](sc, cfg)


class TestKafkaProducerConfig:
    def test_ack_and_partitioner_mapping(self):
        from veneur_tpu.sinks.kafka import ProducerConfig
        kw = ProducerConfig(require_acks="local").kafka_python_kwargs()
        assert kw["acks"] == 1
        kw = ProducerConfig(require_acks="none").kafka_python_kwargs()
        assert kw["acks"] == 0
        # unknown ack level falls back to all (kafka.go:155-158)
        kw = ProducerConfig(require_acks="bogus").kafka_python_kwargs()
        assert kw["acks"] == "all"
        kw = ProducerConfig(partitioner="random").kafka_python_kwargs()
        assert callable(kw["partitioner"])
        assert kw["partitioner"](b"k", [0, 1, 2], [1, 2]) in (1, 2)

    def test_from_config_reads_reference_keys(self):
        from veneur_tpu.sinks.kafka import ProducerConfig
        pc = ProducerConfig.from_config({
            "metric_require_acks": "local",
            "partitioner": "random",
            "retry_max": 7,
            "metric_buffer_bytes": 1024,
            "metric_buffer_messages": 50,
            "metric_buffer_frequency": "500ms",
        }, "metric")
        assert pc.require_acks == "local"
        assert pc.partitioner == "random"
        assert pc.retry_max == 7
        kw = pc.kafka_python_kwargs()
        assert kw["batch_size"] == 1024
        assert kw["linger_ms"] == 500
        assert kw["retries"] == 7
        # the reference misspells span_buffer_mesages; both spellings work
        pc2 = ProducerConfig.from_config({"span_buffer_mesages": 9}, "span")
        assert pc2.buffer_messages == 9


class TestCortexMonotonic:
    def test_counters_accumulate_across_flushes(self, fake):
        from veneur_tpu.sinks.cortex import (
            CortexMetricSink, decode_write_request)
        sink = CortexMetricSink("cortex", url=fake.url, hostname="ch",
                                convert_counters_to_monotonic=True)
        sink.flush([im("req", 3, MetricType.COUNTER, tags=["a:b"]),
                    im("g", 1, MetricType.GAUGE)])
        sink.flush([im("req", 4, MetricType.COUNTER, tags=["a:b"])])
        first = decode_write_request(
            vhttp.snappy_decode(fake.requests[0][2]))
        second = decode_write_request(
            vhttp.snappy_decode(fake.requests[1][2]))
        by_name_1 = {l["__name__"]: v for l, v, _ in first}
        by_name_2 = {l["__name__"]: v for l, v, _ in second}
        assert by_name_1["req"] == 3  # running total after first flush
        assert by_name_1["g"] == 1  # gauges pass through untouched
        assert by_name_2["req"] == 7  # 3 + 4: monotonic, not per-interval


class TestCloudWatchUnitTag:
    def test_unit_tag_sets_unit_and_drops_dimension(self, fake):
        from veneur_tpu.sinks.cloudwatch import CloudWatchMetricSink
        sink = CloudWatchMetricSink("cloudwatch", endpoint=fake.url + "/",
                                    namespace="ns")
        sink.flush([im("cw.t", 1.0, MetricType.GAUGE,
                       tags=["cloudwatch_standard_unit:Seconds",
                             "az:us-1a", "illegal-no-colon"])])
        params = dict(urllib.parse.parse_qsl(fake.requests[0][2].decode()))
        assert params["MetricData.member.1.Unit"] == "Seconds"
        dims = {v for k, v in params.items() if "Dimensions" in k}
        assert "cloudwatch_standard_unit" not in dims
        assert "illegal-no-colon" not in dims
        assert params["MetricData.member.1.Dimensions.member.1.Name"] == "az"


class TestSplunkBatching:
    def test_batch_size_splits_bodies(self, fake):
        from veneur_tpu.sinks.splunk import SplunkSpanSink
        sink = SplunkSpanSink("splunk", hec_address=fake.url, token="t",
                              hostname="h", batch_size=2,
                              submission_workers=3)
        for tid in range(1, 6):
            sink.ingest(make_span(trace_id=tid))
        sink.flush()
        assert len(fake.requests) == 3  # ceil(5/2)
        total = sum(len(b.splitlines()) for _, _, b in fake.requests)
        assert total == 5


class TestLightstepMaxSpans:
    def test_maximum_spans_bounds_buffer(self, fake):
        from veneur_tpu.sinks.lightstep import LightStepSpanSink
        sink = LightStepSpanSink("ls", access_token="t",
                                 collector_url=fake.url,
                                 maximum_spans=3)
        for sid in range(10):
            sink.ingest(make_span(trace_id=1, span_id=sid + 1))
        assert sink.dropped_total == 7
        sink.flush()
        payload = json.loads(fake.requests[0][2])
        spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert len(spans) == 3


class TestNewRelicEvents:
    def test_service_checks_become_custom_events(self, fake):
        from veneur_tpu.sinks.newrelic import NewRelicMetricSink
        sink = NewRelicMetricSink(
            "nr", insert_key="k", hostname="nh", interval=10.0,
            metric_url=fake.url + "/metric", account_id=42,
            event_url=fake.url + "/events")
        sink.flush([im("svc.up", 2, MetricType.STATUS, tags=["env:prod"]),
                    im("g", 1, MetricType.GAUGE)])
        by_path = {p: json.loads(b) for p, _, b in fake.requests}
        events = by_path["/events"]
        assert events[0]["eventType"] == "veneurCheck"
        assert events[0]["status"] == "CRITICAL"
        assert events[0]["statusCode"] == 2
        assert events[0]["env"] == "prod"
        metrics = by_path["/metric"][0]["metrics"]
        assert [m["name"] for m in metrics] == ["g"]

    def test_dogstatsd_events_flush_with_event_type(self, fake):
        from veneur_tpu.samplers.parser import Event
        from veneur_tpu.sinks.newrelic import NewRelicMetricSink
        sink = NewRelicMetricSink(
            "nr", insert_key="k", hostname="nh", interval=10.0,
            metric_url=fake.url + "/metric", event_type="myEvents",
            event_url=fake.url + "/events")
        sink.flush_other_samples([
            Event(name="deploy", message="done", timestamp=5,
                  tags={"env": "prod"})])
        events = json.loads(fake.requests[0][2])
        assert events[0]["eventType"] == "myEvents"
        assert events[0]["name"] == "deploy"
        assert events[0]["env"] == "prod"

    def test_events_dropped_without_account(self, fake):
        from veneur_tpu.sinks.newrelic import NewRelicMetricSink
        sink = NewRelicMetricSink(
            "nr", insert_key="k", hostname="nh", interval=10.0,
            metric_url=fake.url + "/metric")
        sink.flush([im("svc.up", 0, MetricType.STATUS)])
        # no event endpoint configured: nothing POSTed anywhere
        assert fake.requests == []
