"""Forward-tier HA tests: ring health/ejection/readmission, bounded
failover, hedged forwards with idempotency-token dedupe, the durable
carryover spool, and the kill/restore chaos soak the acceptance
criteria pin (one global down for 5 flush intervals at 30 % fault rate,
zero counter loss, llhist bit-exactness)."""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.proxy.destinations import Destination, Destinations
from veneur_tpu.proxy.health import RingHealth
from veneur_tpu.proxy.proxy import create_static_proxy
from veneur_tpu.proxy.ring import ConsistentRing
from veneur_tpu.testing.forwardtest import ForwardTestServer
from veneur_tpu.util.chaos import Chaos
from veneur_tpu.util.spool import CarryoverSpool, frame_metrics, \
    unframe_metrics

pytestmark = pytest.mark.ha


def wait_until(fn, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def mkmetric(name, value=1, tags=()):
    pbm = metric_pb2.Metric(name=name, type=metric_pb2.Counter,
                            scope=metric_pb2.Global)
    pbm.tags.extend(tags)
    pbm.counter.value = value
    return pbm


def make_config(**overrides) -> Config:
    cfg = Config()
    cfg.interval = 10.0
    cfg.hostname = "test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.llhist_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


# -------------------------------------------------------------------------
# Satellite: consistent-hash bounded-movement property
# -------------------------------------------------------------------------


class TestRingProperties:
    def test_eject_bounded_movement_readmit_exact(self):
        """Ejecting 1 of N members remaps <= (1/N + eps) of a 10k-key
        corpus; readmission restores the original assignment EXACTLY
        (identical virtual points recompute from the same address)."""
        n = 5
        ring = ConsistentRing(replicas=200)
        members = [f"host{i}:8128" for i in range(n)]
        ring.set_members(members)
        keys = [f"metric.{i}.{i % 97}" for i in range(10_000)]
        before = {k: ring.get(k) for k in keys}

        victim = members[2]
        ring.remove(victim)
        moved = 0
        for k, owner in before.items():
            new = ring.get(k)
            if new != owner:
                # only the victim's keys may move
                assert owner == victim, (k, owner, new)
                moved += 1
        assert moved / len(keys) <= 1.0 / n + 0.06, moved

        ring.add(victim)
        after = {k: ring.get(k) for k in keys}
        assert after == before  # exact restoration

    def test_walk_at_primary_first_and_distinct(self):
        ring = ConsistentRing(replicas=50)
        ring.set_members(["a:1", "b:1", "c:1"])
        for i in range(200):
            point = ring.point_of(f"k{i}")
            walk = ring.walk_at(point, 3)
            assert walk[0] == ring.get_at(point)
            assert len(walk) == len(set(walk)) == 3


# -------------------------------------------------------------------------
# Ring health: probes, ejection, readmission, membership refresh
# -------------------------------------------------------------------------


class TestRingHealth:
    def _pool(self, addresses):
        dests = Destinations(flush_interval=0.1)
        dests.set_destinations(addresses)
        return dests

    def test_tcp_probe_ejects_dead_and_readmits(self):
        ft1 = ForwardTestServer(lambda ms: None)
        ft1.start()
        ft2 = ForwardTestServer(lambda ms: None)
        ft2.start()
        dests = self._pool([ft1.address, ft2.address])
        events = []
        health = RingHealth(
            dests, interval=0.05, timeout=0.2, unhealthy_after=2,
            healthy_after=2,
            on_event=lambda kind, **f: events.append((kind, f)))
        try:
            health.run_round()
            assert dests.ejected_addresses() == []

            port = ft1.port
            ft1.stop()
            health.run_round()
            assert dests.ejected_addresses() == []  # 1 failure < threshold
            health.run_round()
            assert dests.ejected_addresses() == [ft1.address]
            assert ft1.address not in dests.ring.members()
            assert ft1.address in dests.addresses()  # pool entry survives
            assert ("ring_ejection",
                    {"destination": ft1.address,
                     "consecutive_failures": 2}) in events

            # keys now hash only to the survivor
            for i in range(20):
                assert dests.get(f"k{i}").address == ft2.address

            # restore on the SAME port; two passing probes readmit
            ft1 = ForwardTestServer(lambda ms: None,
                                    address=f"127.0.0.1:{port}")
            ft1.start()
            health.run_round()
            assert dests.ejected_addresses() == [ft1.address]
            health.run_round()
            assert dests.ejected_addresses() == []
            assert ft1.address in dests.ring.members()
            assert any(kind == "ring_readmission" for kind, _ in events)
            rows = dict((r[0], r[2]) for r in health.telemetry_rows())
            assert rows["proxy.ring.ejections"] == 1.0
            assert rows["proxy.ring.readmissions"] == 1.0
            assert rows["proxy.ring.ejected"] == 0.0
        finally:
            dests.clear()
            ft1.stop()
            ft2.stop()

    def test_chaos_health_probe_seam_is_deterministic(self):
        """The health_probe chaos seam fails probes without touching a
        socket — the deterministic way to drive the ejection machinery."""
        from veneur_tpu.util import chaos as chaos_mod
        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        dests = self._pool([ft.address])
        health = RingHealth(dests, interval=0.05, unhealthy_after=2,
                            healthy_after=1)
        chaos_mod.install(Chaos(error_rate=1.0, seams=("health_probe",)))
        try:
            health.run_round()
            health.run_round()
            assert dests.ejected_addresses() == [ft.address]
            chaos_mod.install(None)
            health.run_round()
            assert dests.ejected_addresses() == []
        finally:
            chaos_mod.install(None)
            dests.clear()
            ft.stop()

    def test_membership_refresh_each_round(self):
        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        dests = self._pool([])
        refreshed = []

        def refresh():
            refreshed.append(1)
            dests.set_destinations([ft.address])

        health = RingHealth(dests, interval=0.05, refresh=refresh)
        try:
            health.run_round()
            assert refreshed and dests.addresses() == [ft.address]
        finally:
            dests.clear()
            ft.stop()

    def test_discovery_readd_does_not_bypass_ejection(self):
        """set_destinations re-adding an ejected address must NOT sneak
        it back into the ring before the prober readmits it."""
        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        dests = self._pool([ft.address])
        try:
            dests.eject(ft.address)
            assert ft.address not in dests.ring.members()
            dests.set_destinations([ft.address])
            assert ft.address not in dests.ring.members()
            dests.readmit(ft.address)
            assert ft.address in dests.ring.members()
        finally:
            dests.clear()
            ft.stop()


# -------------------------------------------------------------------------
# Failover routing past a sick primary
# -------------------------------------------------------------------------


class TestFailoverRouting:
    def test_open_breaker_rehomes_key_to_next_healthy(self):
        ft1 = ForwardTestServer(lambda ms: None)
        ft1.start()
        ft2 = ForwardTestServer(lambda ms: None)
        ft2.start()
        dests = Destinations(flush_interval=0.1)
        dests.set_destinations([ft1.address, ft2.address])
        try:
            # find a key owned by ft1
            key = next(f"k{i}" for i in range(1000)
                       if dests.ring.get(f"k{i}") == ft1.address)
            primary = dests._pool[ft1.address]
            assert dests.get(key) is primary
            # trip the primary's breaker: the key re-homes to ft2
            for _ in range(primary.breaker.failure_threshold):
                primary.breaker.record_failure()
            assert dests.get(key).address == ft2.address
            assert dests.failover_routed_total > 0
            # recovery restores the original owner
            primary.breaker.record_success()
            assert dests.get(key) is primary
        finally:
            dests.clear()
            ft1.stop()
            ft2.stop()

    def test_all_sick_falls_back_to_primary_accounting(self):
        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        dests = Destinations(flush_interval=0.1)
        dests.set_destinations([ft.address])
        try:
            dest = dests._pool[ft.address]
            for _ in range(dest.breaker.failure_threshold):
                dest.breaker.record_failure()
            # sole member sick: the primary still answers (its send()
            # sheds and counts) instead of raising
            assert dests.get("anything") is dest
        finally:
            dests.clear()
            ft.stop()


# -------------------------------------------------------------------------
# Hedged forwards + idempotency-token dedupe
# -------------------------------------------------------------------------


class TestHedgedForwards:
    def test_slow_primary_hedges_to_peer(self):
        """A primary whose handler stalls past the hedge budget fires
        the same batch (same token) at the peer; the peer delivers."""
        slow_received, fast_received = [], []

        def slow_handler(ms):
            time.sleep(1.0)
            slow_received.extend(ms)

        slow = ForwardTestServer(slow_handler)
        slow.start()
        fast = ForwardTestServer(fast_received.extend)
        fast.start()
        peer = Destination(fast.address, on_close=lambda d: None,
                           flush_interval=0.1)
        dest = Destination(slow.address, on_close=lambda d: None,
                           flush_interval=0.1, hedge_after=0.15,
                           hedge_peer=lambda: peer)
        try:
            # pin both senders to V2 first (ForwardTestServer is
            # V2-only) so the hedged path exercises the stream future
            dest.send_now([mkmetric("pin.a", 1)], token="")
            peer.send_now([mkmetric("pin.b", 1)], token="")
            assert wait_until(lambda: len(fast_received) == 1, timeout=5)

            dest.send(mkmetric("hedged.m", 7))
            assert wait_until(
                lambda: any(m.name == "hedged.m" for m in fast_received),
                timeout=5)
            assert dest.hedge_fired_total == 1
            assert dest.hedge_wins_total == 1
            # delivery is credited to the node that absorbed it, and the
            # blown budget counts as a failure signal for the primary —
            # a node that never answers inside the budget must
            # eventually trip its breaker and fail over
            assert peer.sent_total >= 1
            assert dest.breaker.consecutive_failures == 1
        finally:
            dest.close()
            peer.close()
            slow.stop()
            fast.stop()

    def test_chaos_latency_fires_hedge_deterministically(self):
        """chaos_forward_latency_ms >= hedge_after burns the budget
        inside the timed window, so the hedge fires every batch — no
        probabilistic rolls, the knob's whole point."""
        from veneur_tpu.util import chaos as chaos_mod

        fast_received = []
        primary_srv = ForwardTestServer(lambda ms: None)
        primary_srv.start()
        fast = ForwardTestServer(fast_received.extend)
        fast.start()
        peer = Destination(fast.address, on_close=lambda d: None,
                           flush_interval=0.1)
        dest = Destination(primary_srv.address, on_close=lambda d: None,
                           flush_interval=0.1, hedge_after=0.1,
                           hedge_peer=lambda: peer)
        chaos_mod.install(Chaos(forward_latency_ms=300.0,
                                seams=("forward_send",)))
        try:
            peer.send_now([mkmetric("pin.p", 1)], token="")  # pin V2
            dest.send_now([mkmetric("pin.d", 1)], token="")  # pin V2
            dest.send(mkmetric("det.hedge", 3))
            assert wait_until(
                lambda: any(m.name == "det.hedge" for m in fast_received),
                timeout=5)
            assert dest.hedge_fired_total == 1
        finally:
            chaos_mod.install(None)
            dest.close()
            peer.close()
            primary_srv.stop()
            fast.stop()

    def test_ready_state_before_first_probe_round(self):
        """A just-started proxy with a healthy pool must be ready even
        though no probe round has populated the member table yet."""
        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        proxy = create_static_proxy([ft.address],
                                    health_check_interval=3600.0)
        proxy.start()  # probe loop won't tick within the test
        try:
            ready, body = proxy.ready_state()
            assert ready is True
            assert body["destinations"] == 1
        finally:
            proxy.stop()
            ft.stop()

    def test_import_server_token_dedupe(self):
        """The global import server applies a token once: a duplicate
        RPC (hedge or at-least-once retry) is acked-and-dropped."""
        from veneur_tpu.core.server import Server
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.forward.wire import token_metadata, _frame_v1
        from veneur_tpu.sinks.channel import ChannelMetricSink

        cfg = make_config(grpc_address="127.0.0.1:0")
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            assert wait_until(lambda: server.import_server is not None)
            imp = server.import_server
            client = ForwardClient(imp.address, deadline=5.0)
            body = _frame_v1(
                mkmetric("dedupe.c", 5).SerializeToString())
            md = token_metadata("tok:1")
            client._send_v1(body, timeout=5.0, metadata=md)
            client._send_v1(body, timeout=5.0, metadata=md)      # dup
            client._send_v1(body, timeout=5.0,
                            metadata=token_metadata("tok:2"))    # fresh
            assert imp.duplicates_dropped_total == 1
            assert imp.imported_total == 2
            rows = imp.telemetry_rows()
            assert rows[0][0] == "forward.hedge.duplicates_dropped"
            assert rows[0][2] == 1.0
            client.close()
        finally:
            server.shutdown()

    def test_failed_attempt_forgets_token_so_retry_passes(self):
        from veneur_tpu.forward.wire import TokenDeduper

        class Ctx:
            def __init__(self, token):
                self._md = (("x-veneur-idempotency-token", token),)

            def invocation_metadata(self):
                return self._md

        dd = TokenDeduper(cache_max=8)
        token, disp = dd.begin(Ctx("t1"))
        assert (token, disp) == ("t1", "fresh")
        # a racing second attempt while the first is mid-merge must NOT
        # be acked (the first may still fail): it fails retryable
        _, disp = dd.begin(Ctx("t1"))
        assert disp == "inflight"
        dd.end(token, ok=False)             # merge failed: forget it
        token, disp = dd.begin(Ctx("t1"))
        assert disp == "fresh"              # retry passes
        dd.end(token, ok=True)
        _, disp = dd.begin(Ctx("t1"))
        assert disp == "done"               # now it's a duplicate
        assert dd.duplicates_dropped_total == 1

    def test_proxy_dedupes_retried_sends(self):
        """The exactly-once-per-node property holds at the PROXY
        boundary too: a retried V1 body with the same token routes
        once."""
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.forward.wire import token_metadata, _frame_v1

        got = []
        ft = ForwardTestServer(got.extend)
        ft.start()
        proxy = create_static_proxy([ft.address],
                                    health_check_interval=0)
        proxy.start()
        try:
            client = ForwardClient(proxy.address, deadline=5.0)
            body = _frame_v1(mkmetric("pd.c", 4).SerializeToString())
            md = token_metadata("ptok:1")
            client._send_v1(body, timeout=5.0, metadata=md)
            client._send_v1(body, timeout=5.0, metadata=md)  # retry dup
            proxy.destinations.flush_wait()
            assert wait_until(
                lambda: sum(1 for m in got if m.name == "pd.c") == 1)
            time.sleep(0.2)  # a second routed copy would land by now
            assert sum(1 for m in got if m.name == "pd.c") == 1
            assert proxy.stats.get("duplicates_dropped_total") == 1
            client.close()
        finally:
            proxy.stop()
            ft.stop()


# -------------------------------------------------------------------------
# Durable carryover spool
# -------------------------------------------------------------------------


class TestSpool:
    def test_framing_roundtrip(self):
        ms = [b"", b"a", b"x" * 1000]
        assert unframe_metrics(frame_metrics(ms)) == ms
        with pytest.raises(ValueError):
            unframe_metrics(b"\x0b\x01a")  # wrong tag
        with pytest.raises(ValueError):
            unframe_metrics(b"\x0a\x05ab")  # truncated body

    def test_append_drain_and_restart_replay(self, tmp_path):
        spool = CarryoverSpool(str(tmp_path))
        spool.append([b"m1", b"m2"])
        spool.append([b"m3"])
        assert spool.depth == 2 and spool.spilled_metrics_total == 3
        seg = spool.oldest()
        assert seg.read_metrics() == [b"m1", b"m2"]  # oldest first
        spool.pop(seg)
        assert spool.depth == 1 and spool.drained_metrics_total == 2

        # a new process over the same directory replays what's left
        spool2 = CarryoverSpool(str(tmp_path))
        assert spool2.depth == 1 and spool2.replayed_total == 1
        assert spool2.oldest().read_metrics() == [b"m3"]

    def test_restart_seeds_sequence_past_disk(self, tmp_path):
        """A restarted spool must not reuse low sequence numbers: the
        name sort IS the drain/shed order, so interleaving a new
        spill-00000001 among a predecessor's segments would break
        oldest-first."""
        a = CarryoverSpool(str(tmp_path))
        a.append([b"old1"])
        a.append([b"old2"])
        b = CarryoverSpool(str(tmp_path))   # "restart"
        b.append([b"new1"])
        assert b.oldest().read_metrics() == [b"old1"]
        names = sorted(f for f in os.listdir(str(tmp_path))
                       if f.endswith(".vspool"))
        # the new segment's name sorts strictly after both replayed ones
        assert names[-1].startswith("spill-00000003-")
        c = CarryoverSpool(str(tmp_path))
        assert [seg.read_metrics() for seg in c._segments] == \
            [[b"old1"], [b"old2"], [b"new1"]]

    def test_bounds_shed_oldest(self, tmp_path):
        spool = CarryoverSpool(str(tmp_path), max_segments=2)
        spool.append([b"a"])
        spool.append([b"b"])
        spool.append([b"c"])
        assert spool.depth == 2
        assert spool.shed_total == 1 and spool.shed_metrics_total == 1
        assert spool.oldest().read_metrics() == [b"b"]  # oldest shed

    def test_carryover_spills_instead_of_shedding(self, tmp_path):
        from veneur_tpu.core.columnstore import RowMeta
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.samplers.metrics import MetricScope
        from veneur_tpu.util.resilience import Carryover

        spilled = []
        co = Carryover(max_intervals=1, spill=lambda fwd: spilled.append(fwd))

        def one(name, value):
            meta = RowMeta(name=name, tags=[], joined_tags="", digest32=1,
                           scope=MetricScope.GLOBAL_ONLY,
                           wire_type="counter")
            return ForwardableState(counters=[(meta, value)])

        co.stash(one("s.c", 1.0))
        assert not spilled and co.depth == 1
        co.stash(one("s.c", 2.0))           # past the bound: spills
        assert co.depth == 0 and co.shed_total == 0
        assert co.spilled_total == 1
        (fwd,), = (spilled,)
        assert fwd.counters[0][1] == 3.0    # merged before the spill

    def test_forward_client_spool_end_to_end(self, tmp_path):
        """Dead upstream: intervals spill to disk past the carryover
        bound; once the upstream returns, the spool drains oldest-first
        and the receiver sees every counter delta exactly once."""
        from veneur_tpu.core.columnstore import RowMeta
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.samplers.metrics import MetricScope
        from veneur_tpu.util.resilience import (Carryover, CircuitBreaker,
                                                RetryPolicy)

        received = []
        ft = ForwardTestServer(received.extend)
        port = ft.port  # bind later: upstream starts DEAD
        spool = CarryoverSpool(str(tmp_path))
        client = ForwardClient(
            f"127.0.0.1:{port}", deadline=3.0,
            retry=RetryPolicy(max_attempts=1),
            breaker=CircuitBreaker(failure_threshold=10_000, name="t"),
            carryover=Carryover(max_intervals=1),
            spool=spool)

        def one(value):
            meta = RowMeta(name="spool.cnt", tags=[], joined_tags="",
                           digest32=1, scope=MetricScope.GLOBAL_ONLY,
                           wire_type="counter")
            return ForwardableState(counters=[(meta, value)])

        try:
            sent = 0
            for v in (1.0, 2.0, 4.0, 8.0):
                client.forward(one(v))
                sent += v
            # intervals 3+ overflowed carryover into the spool
            assert spool.depth >= 1
            assert client.carryover.spilled_total >= 1

            ft.start()
            sent += 16.0
            got = client.forward(one(16.0))
            # the channel may be inside its (capped, <=2s) reconnect
            # backoff right after the restart: the failed interval is
            # stashed, so empty follow-up forwards deliver it
            from veneur_tpu.core.flusher import ForwardableState
            deadline = time.time() + 15.0
            while got == 0 and time.time() < deadline:
                time.sleep(0.3)
                got = client.forward(ForwardableState())
            assert got > 0
            assert spool.depth == 0         # drained after recovery
            assert wait_until(lambda: sum(
                p.counter.value for p in received
                if p.name == "spool.cnt") == sent)
            assert not [f for f in os.listdir(str(tmp_path))
                        if f.endswith(".vspool")]
        finally:
            client.close()
            ft.stop()

    def test_spool_replay_after_restart_drains(self, tmp_path):
        """A 'restarted' client (fresh objects, same spool dir) delivers
        segments a previous process left behind."""
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.util.resilience import RetryPolicy

        old = CarryoverSpool(str(tmp_path))
        old.append([mkmetric("replay.c", 9).SerializeToString()])

        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        spool = CarryoverSpool(str(tmp_path))
        assert spool.replayed_total == 1
        client = ForwardClient(ft.address, deadline=3.0,
                               retry=RetryPolicy(max_attempts=1),
                               spool=spool)
        try:
            # an empty interval still probes-and-drains the spool
            from veneur_tpu.core.flusher import ForwardableState
            assert client.forward(ForwardableState()) == 1
            assert spool.depth == 0
            assert wait_until(lambda: sum(
                p.counter.value for p in received
                if p.name == "replay.c") == 9)
        finally:
            client.close()
            ft.stop()


# -------------------------------------------------------------------------
# Satellite: Destination.close() drains (and counts) before unregistering
# -------------------------------------------------------------------------


class TestDestinationCloseDrain:
    def test_close_counts_inflight_and_unregisters_after(self, monkeypatch):
        from veneur_tpu.core.latency import LatencyObservatory

        # sender thread parked so enqueued metrics stay in the queue
        monkeypatch.setattr(Destination, "_run", lambda self: None)
        ft = ForwardTestServer(lambda ms: None)
        ft.start()
        obs = LatencyObservatory(enabled=True)
        dest = Destination(ft.address, on_close=lambda d: None,
                           observatory=obs)
        qname = f"proxy_dest:{ft.address}"
        hist = obs.queue_hist(qname)
        try:
            for i in range(3):
                assert dest.send(mkmetric(f"d{i}", i))
            assert qname in obs.report()["queues"]
            dest.close()
            # queued items were drained: counted dropped, dwell observed
            # into the still-registered series, THEN unregistered
            assert dest.dropped_total == 3
            assert hist.count == 3
            assert qname not in obs.report()["queues"]
        finally:
            ft.stop()


# -------------------------------------------------------------------------
# Satellite: proxy /healthcheck/ready 503 + member table
# -------------------------------------------------------------------------


class TestProxyReadyEndpoint:
    def test_503_while_majority_ejected(self):
        from veneur_tpu.core.httpapi import HTTPApi

        ft1 = ForwardTestServer(lambda ms: None)
        ft1.start()
        ft2 = ForwardTestServer(lambda ms: None)
        ft2.start()
        proxy = create_static_proxy([ft1.address, ft2.address],
                                    health_check_interval=0)
        proxy.start()
        # no probe thread (interval=0): drive rounds by hand for
        # deterministic ejection state
        health = RingHealth(proxy.destinations, interval=0.05,
                            unhealthy_after=1, healthy_after=1)
        proxy.ring_health = health
        api = HTTPApi({}, server=None, address="127.0.0.1:0",
                      ready=proxy.ready_state)
        api.start()
        host, port = api.address
        url = f"http://{host}:{port}/healthcheck/ready"
        try:
            health.run_round()
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200

            ft1.stop()
            ft2.stop()
            health.run_round()  # both die in one round (threshold 1)
            try:
                urllib.request.urlopen(url, timeout=5)
                assert False, "expected 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
                body = json.loads(e.read())
            assert body["ready"] is False
            assert "ejected" in body["reason"]
            assert body["destinations"] == 2 and body["ejected"] == 2
            table = {m["address"]: m for m in body["members"]}
            assert all(m["ejected"] for m in table.values())
        finally:
            api.stop()
            proxy.stop()


# -------------------------------------------------------------------------
# Chaos: the deterministic slow-destination knob
# -------------------------------------------------------------------------


class TestChaosForwardLatency:
    def test_forward_latency_ms_is_deterministic(self):
        slept = []
        c = Chaos(forward_latency_ms=40.0, sleep=slept.append)
        for _ in range(5):
            c.inject("forward_send")
        assert slept == [0.04] * 5
        assert c.injected_delays["forward_send"] == 5
        c.inject("sink_flush")  # other seams unaffected
        assert slept == [0.04] * 5

    def test_from_config(self):
        cfg = make_config(chaos_enabled=True,
                          chaos_forward_latency_ms=25.0)
        c = Chaos.from_config(cfg)
        assert c.forward_latency_ms == 25.0


# -------------------------------------------------------------------------
# Acceptance soaks
# -------------------------------------------------------------------------


class TestKillRestoreSoak:
    def _run(self, kill_rounds, rounds, error_rate, seed=7):
        """Local server -> global stub. The global dies for
        `kill_rounds` consecutive flush intervals mid-stream while the
        forward seam also injects faults; returns (counter total,
        llhist bin total, sent counter total, sent llhist bins,
        spool depth, latency report during run)."""
        from veneur_tpu.core.server import Server
        from veneur_tpu.forward import llhistwire
        from veneur_tpu.sinks.channel import ChannelMetricSink

        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        port = ft.port
        import tempfile
        spool_dir = tempfile.mkdtemp(prefix="veneur-spool-")
        server = None
        try:
            cfg = make_config(
                forward_address=ft.address,
                chaos_enabled=error_rate > 0,
                chaos_error_rate=error_rate,
                chaos_seams=["forward_send"],
                chaos_seed=seed,
                forward_retry_max_attempts=1,
                # tight carryover bound so the spool engages during the
                # kill window; the breaker must never refuse (a refusal
                # is just another stash, but keep the soak simple)
                carryover_max_intervals=1,
                carryover_spool_dir=spool_dir,
                circuit_breaker_failure_threshold=10_000,
                # conservation accounting instead of bespoke per-seam
                # counting: strict mode raises out of flush() on ANY
                # unexplained imbalance, so the kill window, the spool
                # spill/drain, and the restore all balance per interval
                ledger_strict=True,
                ledger_history=64)
            server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
            server.start()
            sent_counter = 0
            sent_bins = np.zeros(0, np.int64)
            kill_at = 2
            lat_report_mid = None
            for rnd in range(rounds):
                if rnd == kill_at:
                    ft.stop()
                if rnd == kill_at + kill_rounds:
                    ft = ForwardTestServer(received.extend,
                                           address=f"127.0.0.1:{port}")
                    ft.start()
                delta = 3 + rnd
                server.handle_metric_packet(
                    b"soak.count:%d|c|#veneurglobalonly" % delta)
                sent_counter += delta
                server.handle_metric_packet(b"soak.lat:%d|l" % (rnd + 1))
                from veneur_tpu.core.latency import bin_index_scalar
                from veneur_tpu.ops import llhist_ref
                bins = np.zeros(llhist_ref.BINS, np.int64)
                bins[bin_index_scalar(float(rnd + 1))] += 1
                sent_bins = bins if sent_bins.size == 0 else sent_bins + bins
                server.flush()
                if rnd == kill_at + 1:
                    lat_report_mid = server.latency.report()
            # drain: chaos off, everything owed must deliver. The
            # restored node needs one (capped, <=2s) reconnect-backoff
            # window before the channel redials, so pace the flushes.
            if server.chaos is not None:
                server.chaos.enabled = False
            for _ in range(10):
                server.flush()
                if (server.forward_client.carryover.depth == 0
                        and server.forward_client.spool.depth == 0):
                    break
                time.sleep(0.5)
            assert server.forward_client.carryover.depth == 0
            assert server.forward_client.spool.depth == 0
            got_counter = [0]
            got_bins = np.zeros(sent_bins.shape, np.int64)

            def settle():
                got_counter[0] = sum(p.counter.value for p in received
                                     if p.name == "soak.count")
                return got_counter[0] >= sent_counter
            wait_until(settle, timeout=10.0)
            for p in received:
                if p.name == "soak.lat":
                    got_bins += llhistwire.unmarshal(p.llhist.bins)
            # zero unexplained ledger imbalance at every stage, every
            # interval — one dead global, forward faults, spool drain
            # to empty all explained (strict already raised on a live
            # breach; this pins the recorded history and the net)
            for interval in server.ledger.history_imbalances():
                assert all(v == 0.0 for v in interval.values()), interval
            assert all(v == 0.0 for v in
                       server.ledger.imbalance_net.values())
            spool_depth = server.forward_client.spool.depth
            return (got_counter[0], got_bins, sent_counter, sent_bins,
                    spool_depth, lat_report_mid, server, spool_dir)
        finally:
            if server is not None:
                server.shutdown()
            ft.stop()

    def test_kill_restore_fast(self):
        """Tier-1 pin: global down 2 intervals, no extra faults — zero
        counter loss via carryover+spool, llhist registers exact."""
        (got, got_bins, sent, sent_bins, depth, lat_mid, server,
         spool_dir) = self._run(kill_rounds=2, rounds=6, error_rate=0.0)
        assert got == sent
        assert np.array_equal(got_bins, sent_bins)
        assert depth == 0
        # the spool queue was registered while the server ran...
        assert "forward_spool" in (lat_mid or {}).get("queues", {})
        # ...and unregistered cleanly at shutdown
        assert "forward_spool" not in server.latency.report()["queues"]

    @pytest.mark.slow
    @pytest.mark.chaos
    def test_acceptance_soak_kill_5_intervals_30pct_faults(self):
        """The acceptance soak: one global instance dead for 5 flush
        intervals mid-stream with a 30 % injected fault rate on the
        forward seam; after restore, zero counter loss and llhist
        bit-exactness versus the unfaulted control run."""
        (got_c, bins_c, sent_c, sbins_c, depth_c, lat_mid, server,
         _d) = self._run(kill_rounds=5, rounds=12, error_rate=0.3)
        (got_0, bins_0, sent_0, sbins_0, depth_0, _l, _s,
         _d0) = self._run(kill_rounds=0, rounds=12, error_rate=0.0)
        assert sent_c == sent_0
        assert got_0 == sent_0                    # control baseline
        assert got_c == sent_c                    # zero counter loss
        assert np.array_equal(sbins_c, sbins_0)
        assert np.array_equal(bins_0, sbins_0)    # control exact
        assert np.array_equal(bins_c, sbins_c)    # llhist bit-exact
        assert depth_c == 0 and depth_0 == 0
        assert "forward_spool" in (lat_mid or {}).get("queues", {})


class TestRingFailoverSoak:
    def _driver(self):
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "ring_failover_soak.py")
        spec = importlib.util.spec_from_file_location(
            "ring_failover_soak", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_driver_quick(self):
        """The standalone driver's invariants hold on a short run."""
        report = self._driver().run_soak(
            rounds=6, per_round=40, kill_round=2, restore_round=4,
            probe_interval=0.05)
        assert report["loss_unaccounted"] == 0
        assert report["proxy"]["received_total"] == report["sent"]
        assert any(e["event"] == "ejected" for e in report["events"])
        assert any(e["event"] == "readmitted" for e in report["events"])

    @pytest.mark.slow
    def test_driver_soak(self):
        report = self._driver().run_soak(
            rounds=16, per_round=250, kill_round=4, restore_round=10,
            probe_interval=0.05)
        assert report["loss_unaccounted"] == 0
        # loss is confined to the kill->ejection detection window
        assert report["detection_window_loss"] <= 2 * 250, report
