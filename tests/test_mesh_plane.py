"""Sharded mesh serving-plane tests: digest-home routing, the
partitioned scalar/llhist families' exactness pins (bit-identical to
single-device), the shard-group ring's failure confinement, the
proxy-tier interval-stamp carry, and the chip-failure soak (one shard
group member ejected for 3 intervals under 30 % forward faults — zero
counter loss, group-confined re-homing, strict ledgers clean)."""

import time

import jax
import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.columnstore import ColumnStore
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.ops import llhist_ref
from veneur_tpu.proxy.ring import (ConsistentRing, EmptyRingError,
                                   ShardGroupRing, parse_shard_suffix)
from veneur_tpu.samplers.parser import Parser
from veneur_tpu.testing.forwardtest import ForwardTestServer

pytestmark = pytest.mark.mesh


def wait_until(fn, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


def parse_into(store_process, packets):
    parser = Parser()
    for pkt in packets:
        parser.parse_metric_fast(pkt, store_process)


def collect_stubs(packets):
    """Parsed UDPMetric stubs (the import-path merge_batch input)."""
    parser = Parser()
    out = []
    for pkt in packets:
        parser.parse_metric_fast(pkt, out.append)
    return out


# -------------------------------------------------------------------------
# Digest-home routing
# -------------------------------------------------------------------------


class TestDigestRouting:
    def test_home_assignment_stamped_at_mint(self):
        store = ColumnStore(counter_capacity=64, llhist_capacity=64,
                            batch_cap=32, shard_devices=4)
        plane = store.shard_plane
        assert plane is not None and plane.n == 4
        stubs = collect_stubs([b"mp.home.%d:1|c" % i for i in range(40)])
        for stub in stubs:
            store.counters.add(stub)
        table = store.counters
        for stub in stubs:
            row = table.rows[(stub.digest64 << 2) | int(stub.scope)]
            assert table._shard_of[row] == plane.home(stub.digest64)
        # every shard serves some keys at this count (4 shards, 40 keys)
        assert len(set(table._shard_of[:40].tolist())) == 4

    def test_llhist_state_partitioned_by_home(self):
        """After dispatch, each row's registers live ONLY on its home
        shard's slice of the stacked state."""
        store = ColumnStore(llhist_capacity=64, batch_cap=16,
                            shard_devices=4)
        parse_into(store.process,
                   [b"mp.part.%d:%d|l" % (i, i + 1) for i in range(30)])
        store.apply_all_pending()
        table = store.llhists
        state = np.asarray(table.state)  # (4, K, BINS_PAD)
        per_shard_mass = state.sum(axis=2)  # (4, K)
        for row in range(30):
            nz = np.flatnonzero(per_shard_mass[:, row])
            assert nz.tolist() == [int(table._shard_of[row])]

    def test_mesh_telemetry_rows(self):
        store = ColumnStore(counter_capacity=64, batch_cap=16,
                            shard_devices=2)
        parse_into(store.process, [b"mp.tel.%d:1|c" % i for i in range(8)])
        store.apply_all_pending()
        rows = {name: value for name, _kind, value, _tags
                in store.telemetry_rows()
                if name.startswith(("mesh.", "shard."))}
        assert rows.get("mesh.shards") == 2.0
        assert rows.get("mesh.batches_dispatched", 0) >= 1
        assert any(name == "shard.samples_routed"
                   for name, *_ in store.telemetry_rows())


# -------------------------------------------------------------------------
# Partitioned-family exactness: sharded == single-device, bit for bit
# -------------------------------------------------------------------------


class TestScalarShardExactness:
    def test_counters_bit_identical(self):
        s1 = ColumnStore(counter_capacity=128, batch_cap=32)
        s4 = ColumnStore(counter_capacity=128, batch_cap=32,
                         shard_devices=4)
        rng = np.random.default_rng(5)
        packets = []
        for i in range(60):
            for _ in range(6):
                packets.append(b"mp.c.%d:%.4f|c|@0.5" % (
                    i % 20, rng.random() * 50))
        parse_into(s1.process, packets)
        parse_into(s4.process, packets)
        # import-path merge rides the host-side f64 accumulator in both
        stubs = collect_stubs([b"mp.c.%d:1|c" % i for i in range(20)])
        s1.counters.merge_batch(stubs, [7.0] * len(stubs))
        s4.counters.merge_batch(stubs, [7.0] * len(stubs))
        s1.apply_all_pending()
        s4.apply_all_pending()
        v1, t1, _ = s1.counters.snapshot_and_reset()
        v4, t4, _ = s4.counters.snapshot_and_reset()
        np.testing.assert_array_equal(t1, t4)
        np.testing.assert_array_equal(v1[t1], v4[t4])  # exact, not close

    def test_gauges_last_write_wins_across_dispatches(self):
        """Interleaved writes spanning many batch dispatches: the home
        shard serializes every key's writes, so the final value matches
        single-device exactly (the property round-robin destroyed)."""
        s1 = ColumnStore(gauge_capacity=64, batch_cap=8)
        s4 = ColumnStore(gauge_capacity=64, batch_cap=8, shard_devices=4)
        packets = []
        for step in range(50):
            for key in range(10):
                packets.append(b"mp.g.%d:%d|g" % (key, step * 10 + key))
        parse_into(s1.process, packets)
        parse_into(s4.process, packets)
        s1.apply_all_pending()
        s4.apply_all_pending()
        v1, t1, _ = s1.gauges.snapshot_and_reset()
        v4, t4, _ = s4.gauges.snapshot_and_reset()
        np.testing.assert_array_equal(t1, t4)
        np.testing.assert_array_equal(v1[t1], v4[t4])

    def test_gauge_import_merge_routed_to_home(self):
        s4 = ColumnStore(gauge_capacity=64, batch_cap=8, shard_devices=4)
        stubs = collect_stubs([b"mp.gi.%d:0|g" % i for i in range(12)])
        s4.gauges.merge_batch(stubs, [float(i * 3) for i in range(12)])
        v4, t4, _ = s4.gauges.snapshot_and_reset()
        got = {i: v4[s4.gauges.rows.get(
            (stub.digest64 << 2) | int(stub.scope))]
               for i, stub in enumerate(stubs)
               if t4[s4.gauges.rows.get(
                   (stub.digest64 << 2) | int(stub.scope))]}
        assert got == {i: pytest.approx(i * 3.0) for i in range(12)}


class TestLLHistShardExactness:
    """The PR-5 bit-exactness pin generalized to the mesh: registers
    ADD across shards, so sharded == single-device exactly."""

    def _feed(self, store):
        rng = np.random.default_rng(11)
        packets = []
        for i in range(25):
            for v in rng.lognormal(3, 1, 6):
                packets.append(b"mp.ll.%d:%.4f|l" % (i, v))
        parse_into(store.process, packets)
        # batch fast path
        rows = []
        parser = Parser()
        for i in range(25):
            parser.parse_metric_fast(
                b"mp.ll.%d:1|l" % i,
                lambda mm: rows.append(store.llhists.intern(mm)))
        vals = rng.lognormal(3, 1, len(rows)).astype(np.float32)
        store.llhists.add_batch(np.asarray(rows, np.int32), vals,
                                np.ones(len(rows), np.float32))
        # import-path register merge
        stubs = collect_stubs([b"mp.ll.%d:1|l" % i for i in range(25)])
        bins = np.zeros((len(stubs), llhist_ref.BINS), np.int64)
        bins[:, llhist_ref.bin_index(np.full(len(stubs), 42.0))] = 5
        store.llhists.merge_batch(stubs, bins)
        store.apply_all_pending()

    def test_registers_and_quantiles_bit_identical(self):
        s1 = ColumnStore(llhist_capacity=64, batch_cap=32)
        s4 = ColumnStore(llhist_capacity=64, batch_cap=32,
                         shard_devices=4)
        self._feed(s1)
        self._feed(s4)
        ps = (0.5, 0.9, 0.99)
        out1, bins1, t1, _ = s1.llhists.snapshot_and_reset(ps)
        out4, bins4, t4, _ = s4.llhists.snapshot_and_reset(ps)
        np.testing.assert_array_equal(t1, t4)
        np.testing.assert_array_equal(bins1, bins4)  # registers exact
        np.testing.assert_array_equal(out1["count"], out4["count"])
        np.testing.assert_array_equal(out1["quantiles"],
                                      out4["quantiles"])

    def test_capacity_growth_while_sharded(self):
        store = ColumnStore(llhist_capacity=8, batch_cap=16,
                            shard_devices=4)
        parse_into(store.process,
                   [b"mp.grow.%d:5|l" % i for i in range(40)])
        store.apply_all_pending()
        out, bins, touched, _ = store.llhists.snapshot_and_reset((0.5,))
        assert int(touched.sum()) == 40
        assert bins.sum() == 40
        assert store.llhists.capacity >= 40


class TestShardedServerFlush:
    def test_flush_parity_with_circllhist_encoding(self):
        """A server-level flush (histogram_encoding=circllhist routes
        timers into the llhist family) must be bit-identical between a
        sharded and a single-device store."""
        from veneur_tpu.core.server import Server
        from veneur_tpu.sinks.channel import ChannelMetricSink

        def config(shards):
            cfg = Config()
            cfg.interval = 60.0
            cfg.statsd_listen_addresses = []
            cfg.percentiles = [0.5, 0.9, 0.99]
            cfg.histogram_encoding = "circllhist"
            cfg.tpu.counter_capacity = 128
            cfg.tpu.gauge_capacity = 128
            cfg.tpu.histo_capacity = 128
            cfg.tpu.set_capacity = 64
            cfg.tpu.llhist_capacity = 128
            cfg.tpu.batch_cap = 64
            cfg.tpu.shards = shards
            return cfg.apply_defaults()

        single = Server(config(1), extra_metric_sinks=[
            s1 := ChannelMetricSink()])
        sharded = Server(config(4), extra_metric_sinks=[
            s4 := ChannelMetricSink()])
        rng = np.random.default_rng(7)
        for i in range(200):
            v = rng.lognormal(3, 1)
            for server in (single, sharded):
                server.handle_metric_packet(
                    b"mp.srv.t%d:%.4f|ms" % (i % 16, v))
                server.handle_metric_packet(b"mp.srv.c:3|c")
                server.handle_metric_packet(
                    b"mp.srv.g%d:%d|g" % (i % 4, i))
        single.store.apply_all_pending()
        sharded.store.apply_all_pending()
        single.flush()
        sharded.flush()
        got1 = {(m.name, tuple(sorted(m.tags))): m.value
                for m in s1.wait_flush()}
        got4 = {(m.name, tuple(sorted(m.tags))): m.value
                for m in s4.wait_flush()}
        assert set(got1) == set(got4)
        for key in got1:
            # llhist registers merge exactly -> every emitted series
            # (percentiles, counts, buckets, counters, gauges) matches
            # bit for bit
            assert got1[key] == got4[key], key

    def test_recycled_spare_keeps_per_device_placement(self):
        """Repeated non-idle flush rounds on the sharded per-device
        families (histo/set) must keep each recycled generation on ITS
        shard device. The donated reset kernels' outputs carry no data
        dependence on their input, so without an explicit out_sharding
        XLA commits them to the default device — the spare list
        collapses onto device 0 and round 3's cross-shard stack raises
        (the round-1 recycle makes the bad spare, round 2 installs it,
        round 3 reads it out)."""
        from veneur_tpu.core.server import Server
        from veneur_tpu.sinks.channel import ChannelMetricSink

        cfg = Config()
        cfg.interval = 60.0
        cfg.statsd_listen_addresses = []
        cfg.tpu.histo_capacity = 128
        cfg.tpu.set_capacity = 64
        cfg.tpu.shards = 2
        server = Server(cfg.apply_defaults(),
                        extra_metric_sinks=[sink := ChannelMetricSink()])
        try:
            for rnd in range(4):
                for i in range(20):
                    server.handle_metric_packet(
                        b"mp.spare.t:%0.1f|ms" % (i + 1.0))
                    server.handle_metric_packet(b"mp.spare.s:m%d|s" % (i % 5))
                server.store.apply_all_pending()
                server.flush()
                got = {m.name: m.value for m in sink.wait_flush()}
                assert got["mp.spare.t.count"] == 20.0, rnd
                assert got["mp.spare.t.max"] == 20.0, rnd
                assert got["mp.spare.s"] == 5.0, rnd
                for table in (server.store.histos, server.store.sets):
                    placements = [
                        next(iter(jax.tree.leaves(st)[0].devices()))
                        for st in table.states]
                    assert len(set(placements)) == len(placements), \
                        (rnd, table.family, placements)
        finally:
            server.config.flush_on_shutdown = False
            server.shutdown()


# -------------------------------------------------------------------------
# Shard-group ring
# -------------------------------------------------------------------------


class TestShardGroupRing:
    def _ring(self):
        ring = ShardGroupRing(2)
        for addr, g in (("g0a:1", 0), ("g0b:1", 0),
                        ("g1a:1", 1), ("g1b:1", 1)):
            ring.assign(addr, g)
            ring.add(addr)
        return ring

    def test_parse_shard_suffix(self):
        assert parse_shard_suffix("h:8128#3") == ("h:8128", 3)
        assert parse_shard_suffix("h:8128") == ("h:8128", None)
        assert parse_shard_suffix("h:8128#x") == ("h:8128#x", None)

    def test_points_partition_into_contiguous_ranges(self):
        ring = self._ring()
        for key in range(1000):
            point = ring.point_of(f"k{key}")
            group = ring.group_of_point(point)
            assert group == (point * 2) >> 64
            owner = ring.get_at(point)
            assert ring.group_of(owner) == group

    def test_eject_confined_to_group_and_readmit_exact(self):
        ring = self._ring()
        keys = [f"mp.key.{i}" for i in range(2000)]
        before = {k: ring.get(k) for k in keys}
        ring.remove("g0a:1")
        after = {k: ring.get(k) for k in keys}
        moved = {k for k in keys if before[k] != after[k]}
        # only g0a's keys moved, and ONLY onto its group sibling
        assert moved == {k for k in keys if before[k] == "g0a:1"}
        assert all(after[k] == "g0b:1" for k in moved)
        # group 1's assignment is untouched
        assert all(after[k] == before[k] for k in keys
                   if ring.group_of(before[k]) == 1)
        ring.add("g0a:1")
        restored = {k: ring.get(k) for k in keys}
        assert restored == before  # identical virtual points

    def test_whole_group_down_spills_clockwise(self):
        ring = self._ring()
        ring.remove("g0a:1")
        ring.remove("g0b:1")
        keys = [f"mp.spill.{i}" for i in range(500)]
        owners = {ring.get(k) for k in keys}
        assert owners <= {"g1a:1", "g1b:1"}
        ring.remove("g1a:1")
        ring.remove("g1b:1")
        with pytest.raises(EmptyRingError):
            ring.get("anything")

    def test_walk_prefers_own_group(self):
        ring = self._ring()
        for key in ("a", "b", "c", "d"):
            point = ring.point_of(key)
            group = ring.group_of_point(point)
            walk = ring.walk_at(point, 4)
            assert len(walk) == 4
            # the key's own group's two members come first
            assert {ring.group_of(m) for m in walk[:2]} == {group}

    def test_live_member_cannot_change_group(self):
        ring = self._ring()
        with pytest.raises(ValueError):
            ring.assign("g0a:1", 1)

    def test_hash_fallback_assignment_is_stable(self):
        ring = ShardGroupRing(4)
        ring.add("h1:1")
        g = ring.group_of("h1:1")
        ring.remove("h1:1")
        ring.add("h1:1")
        assert ring.group_of("h1:1") == g

    def test_group_siblings_confined_and_empty_without_peer(self):
        """Hedge candidates come from the member's OWN group only (a
        cross-group hedge would merge the primary's key range off-range
        silently), and a member with no live group sibling gets none.
        Note plain walk_at(point_of(member)) would start in whatever
        group the address's hash bits land in — the bug this pins."""
        ring = self._ring()
        assert ring.group_siblings("g0a:1", 4) == ["g0b:1"]
        assert ring.group_siblings("g1b:1", 4) == ["g1a:1"]
        ring.remove("g0b:1")
        assert ring.group_siblings("g0a:1", 4) == []

    def test_hedge_peer_group_confined(self):
        from veneur_tpu.proxy.destinations import Destinations

        ft = {}
        for name in ("g0a", "g0b", "g1a"):
            ft[name] = ForwardTestServer(lambda _batch: None)
            ft[name].start()
        dests = Destinations(send_buffer=8, batch=8, flush_interval=0.1,
                             shard_groups=2)
        try:
            dests.set_destinations([f"{ft['g0a'].address}#0",
                                    f"{ft['g0b'].address}#0",
                                    f"{ft['g1a'].address}#1"])
            peer = dests.hedge_peer_for(ft["g0a"].address)
            assert peer is not None
            assert peer.address == ft["g0b"].address
            # a group of one never hedges cross-group
            assert dests.hedge_peer_for(ft["g1a"].address) is None
        finally:
            dests.clear()
            for srv in ft.values():
                srv.stop()

    def test_failover_walk_outside_group_counts_spill(self):
        """A failover walk deep enough to leave the key's group books
        every off-range route in group_spill — not only the empty-group
        clockwise spill at the primary hop."""
        from veneur_tpu.proxy.destinations import Destinations

        ft = {}
        for name in ("g0a", "g1a"):
            ft[name] = ForwardTestServer(lambda _batch: None)
            ft[name].start()
        dests = Destinations(send_buffer=8, batch=8, flush_interval=0.1,
                             shard_groups=2, failover_walk=2)
        try:
            dests.set_destinations([f"{ft['g0a'].address}#0",
                                    f"{ft['g1a'].address}#1"])
            ring = dests.ring
            # a key homed in group 0, with its only member breaker-open
            point = next(p for p in (ring.point_of(f"k{i}")
                                     for i in range(200))
                         if ring.group_of_point(p) == 0)
            primary = dests._pool[ft["g0a"].address]
            for _ in range(primary.breaker.failure_threshold + 1):
                primary.breaker.record_failure()
            before = dests.group_spill_total
            alt = dests.get_at(point)
            assert alt.address == ft["g1a"].address
            assert dests.group_spill_total == before + 1
        finally:
            dests.clear()
            for srv in ft.values():
                srv.stop()


class TestPeerShardsWindow:
    def test_peer_shards_gauge_decays(self):
        """mesh.peer_shards is a rolling two-window max: a local that
        falls back to single-device tables (header gone) rolls the
        window with its notes and the gauge drops to 0 — the
        degraded-mesh runbook's alert, impossible with a lifetime
        max."""
        from veneur_tpu.forward.server import ImportServer

        class Srv:  # minimal duck-typed owner
            trace_plane = None
            store = None

        imp = ImportServer.__new__(ImportServer)
        imp.PEER_SHARDS_WINDOW_S = 60.0
        imp._peer_shards_cur = 0
        imp._peer_shards_prev = 0
        imp._peer_shards_t0 = time.monotonic()

        class Ctx:
            def __init__(self, n):
                self._md = ((("x-veneur-shards", str(n)),)
                            if n else ())

            def invocation_metadata(self):
                return self._md

        imp._note_peer_shards(Ctx(4))
        assert imp.peer_shards == 4
        # sender narrows: notes keep arriving without the header
        imp._peer_shards_t0 -= 61.0
        imp._note_peer_shards(Ctx(0))
        assert imp.peer_shards == 4  # previous window still in view
        imp._peer_shards_t0 -= 61.0
        imp._note_peer_shards(Ctx(0))
        assert imp.peer_shards == 0  # decayed


class TestRingCompat:
    def test_consistent_ring_compat_surface(self):
        """The pool swaps ring implementations; both must expose the
        same call surface."""
        for ring in (ConsistentRing(), ShardGroupRing(2)):
            ring.add("m:1")
            assert ring.members() == ["m:1"]
            assert len(ring) == 1
            point = ring.point_of("k")
            assert ring.get_at(point) == "m:1"
            assert ring.walk_at(point, 2) == ["m:1"]
            ring.set_members(["m:1", "m:2"])
            assert len(ring) == 2
            ring.remove("m:2")
            assert ring.members() == ["m:1"]


# -------------------------------------------------------------------------
# Proxy interval-stamp carry (WAL replay through the routing tier)
# -------------------------------------------------------------------------


def mkmetric(name, value=1):
    pbm = metric_pb2.Metric(name=name, type=metric_pb2.Counter,
                            scope=metric_pb2.Global)
    pbm.counter.value = value
    return pbm


class TestProxyIntervalCarry:
    def test_destination_batches_split_and_stamp_interval(self):
        from veneur_tpu.proxy.destinations import Destinations

        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        dests = Destinations(send_buffer=64, batch=64, flush_interval=0.1)
        try:
            dests.set_destinations([ft.address])
            dest = dests.get("any")
            stale = 1_700_000_000.0
            assert dest.send(mkmetric("mp.live.a"))
            assert dest.send(mkmetric("mp.old.a"), interval=stale)
            assert dest.send(mkmetric("mp.old.b"), interval=stale)
            assert dest.send(mkmetric("mp.live.b"))
            assert wait_until(lambda: len(received) >= 4)
            # the stale run rode its own RPC with the interval stamp;
            # live runs carry none
            stamped = [md for md in ft.call_metadata
                       if "x-veneur-interval" in md]
            assert len(stamped) == 1
            assert float(stamped[0]["x-veneur-interval"]) == stale
            unstamped = [md for md in ft.call_metadata
                         if "x-veneur-interval" not in md]
            assert len(unstamped) == 2
        finally:
            dests.clear()
            ft.stop()

    def test_proxy_handler_carries_interval_to_destination(self):
        from veneur_tpu.proxy.proxy import create_static_proxy

        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        proxy = create_static_proxy(
            [ft.address], health_check_interval=0,
            latency_observatory=False)
        try:
            proxy.start()
            stale = 1_700_000_123.0

            class Ctx:
                def invocation_metadata(self):
                    return (("x-veneur-interval", f"{stale:.3f}"),)

            proxy._send_metrics_v2(iter([mkmetric("mp.carry.a", 3)]),
                                   Ctx())
            assert wait_until(lambda: len(received) >= 1)
            stamped = [md for md in ft.call_metadata
                       if "x-veneur-interval" in md]
            assert stamped and float(
                stamped[0]["x-veneur-interval"]) == stale
        finally:
            proxy.stop()
            ft.stop()


# -------------------------------------------------------------------------
# Chip-failure soak: one shard-group member ejected for 3 intervals
# under 30 % forward faults — zero counter loss, group-confined
# re-homing, strict ledgers clean every interval.
# -------------------------------------------------------------------------


class TestChipFailureSoak:
    def _topology(self):
        from veneur_tpu.proxy.proxy import create_static_proxy

        servers = {}
        received = {}
        for name in ("g0a", "g0b", "g1a", "g1b"):
            received[name] = []
            servers[name] = ForwardTestServer(received[name].extend)
            servers[name].start()
        group_of_addr = {servers["g0a"].address: 0,
                         servers["g0b"].address: 0,
                         servers["g1a"].address: 1,
                         servers["g1b"].address: 1}
        proxy = create_static_proxy(
            [f"{addr}#{g}" for addr, g in group_of_addr.items()],
            shard_groups=2, health_check_interval=0,
            ledger_strict=True)
        proxy.start()
        return servers, received, group_of_addr, proxy

    def test_soak_eject_3_intervals_30pct_faults(self):
        from veneur_tpu.core.server import Server
        from veneur_tpu.sinks.channel import ChannelMetricSink
        from veneur_tpu.util.chaos import Chaos

        servers, received, group_of_addr, proxy = self._topology()
        local = None
        try:
            cfg = Config()
            cfg.interval = 60.0
            cfg.hostname = "mesh-soak"
            cfg.statsd_listen_addresses = []
            cfg.forward_address = proxy.address
            cfg.tpu.counter_capacity = 256
            cfg.tpu.batch_cap = 512
            cfg.forward_retry_max_attempts = 2
            cfg.forward_retry_base = 0.01
            cfg.forward_retry_max = 0.02
            cfg.carryover_max_intervals = 10
            cfg.circuit_breaker_failure_threshold = 10_000
            cfg.ledger_strict = True
            cfg.ledger_history = 64
            local = Server(cfg.apply_defaults(),
                           extra_metric_sinks=[ChannelMetricSink()])
            local.start()
            # 30 % faults on the LOCAL's forward seam only (never
            # installed globally, so the proxy's fault-free senders
            # model a healthy intra-mesh fabric): failed local sends
            # recover via retry + carryover — the zero-loss pin
            local.forward_client.chaos = Chaos(
                enabled=True, error_rate=0.3, seams={"forward_send"},
                seed=23)

            ejected_addr = servers["g0a"].address
            keys = [b"mp.soak.%d" % i for i in range(40)]
            sent = {k.decode(): 0 for k in keys}
            rounds = 8
            eject_at, readmit_at = 2, 5  # 3 ejected intervals
            for rnd in range(rounds):
                if rnd == eject_at:
                    proxy.destinations.eject(ejected_addr)
                if rnd == readmit_at:
                    proxy.destinations.readmit(ejected_addr)
                for j, key in enumerate(keys):
                    delta = rnd + j + 1
                    local.handle_metric_packet(
                        b"%s:%d|c|#veneurglobalonly" % (key, delta))
                    sent[key.decode()] += delta
                local.flush()
                proxy.ledger.close_interval()  # strict: raises on leak
            # drain: faults off, everything owed must deliver
            local.forward_client.chaos = None
            for _ in range(6):
                local.flush()
                if local.forward_client.carryover.depth == 0:
                    break
            assert local.forward_client.carryover.depth == 0

            def totals():
                # only the soak's own keys: the local also forwards its
                # self-metrics (e.g. ssf.names_unique from the native
                # engine), which ride the same path but aren't in `sent`
                got = {}
                for name in servers:
                    for pbm in received[name]:
                        if pbm.name.startswith("mp.soak."):
                            got[pbm.name] = got.get(pbm.name, 0) \
                                + pbm.counter.value
                return got

            assert wait_until(
                lambda: sum(totals().values()) >= sum(sent.values()),
                timeout=15.0)
            proxy.destinations.flush_wait(timeout=5.0)
            got = totals()
            # zero counter loss across ejection + faults + readmission
            assert got == sent
            # strict already raised on any live breach; pin the history
            for interval in local.ledger.history_imbalances():
                assert all(v == 0.0 for v in interval.values()), interval

            # group-confined re-homing: every key that ever landed on a
            # group-0 member belongs to group 0's digest range, group-1
            # members only ever saw group-1 keys, and the ejected
            # member's keys went ONLY to its group sibling
            ring = proxy.destinations.ring
            owners = {}
            for name, srv in servers.items():
                for pbm in received[name]:
                    if pbm.name.startswith("mp.soak."):
                        owners.setdefault(pbm.name, set()).add(srv.address)
            for metric_name, seen in owners.items():
                point = ring.point_of(
                    f"{metric_name}counter")  # name+type+tags key
                home_group = ring.group_of_point(point)
                assert {group_of_addr[a] for a in seen} == {home_group}, \
                    (metric_name, seen)
            # the ejection window re-homed some keys onto the sibling —
            # the failover actually happened
            g0a_keys = {p.name for p in received["g0a"]
                        if p.name.startswith("mp.soak.")}
            g0b_keys = {p.name for p in received["g0b"]
                        if p.name.startswith("mp.soak.")}
            assert g0a_keys & g0b_keys, "no key re-homed during ejection"
        finally:
            if local is not None:
                local.shutdown()
            proxy.stop()
            for srv in servers.values():
                srv.stop()
