"""DogStatsD parser grammar tests; corpus modeled on the reference test
strategy (reference parser_test.go) but authored fresh."""

import pytest

from veneur_tpu.samplers import metrics as m
from veneur_tpu.samplers.parser import ParseError, Parser
from veneur_tpu.util.fnv import fnv1a_32


def parse_one(packet, extend_tags=None):
    out = []
    Parser(extend_tags).parse_metric(packet, out.append)
    assert len(out) == 1
    return out[0]


def parse_all(packet, extend_tags=None):
    out = []
    Parser(extend_tags).parse_metric(packet, out.append)
    return out


class TestBasicMetrics:
    def test_counter(self):
        metric = parse_one(b"a.b.c:1|c")
        assert metric.name == "a.b.c"
        assert metric.type == m.COUNTER
        assert metric.value == 1.0
        assert metric.sample_rate == 1.0
        assert metric.tags == []
        assert metric.scope == m.MetricScope.MIXED

    def test_gauge(self):
        assert parse_one(b"x:3.5|g").type == m.GAUGE

    def test_histogram_h_and_d(self):
        assert parse_one(b"x:1|h").type == m.HISTOGRAM
        assert parse_one(b"x:1|d").type == m.HISTOGRAM

    def test_timer(self):
        metric = parse_one(b"lat:250|ms")
        assert metric.type == m.TIMER
        assert metric.value == 250.0

    def test_set_keeps_string_value(self):
        metric = parse_one(b"users:abc|s")
        assert metric.type == m.SET
        assert metric.value == "abc"

    def test_negative_and_float_values(self):
        assert parse_one(b"x:-17.5|g").value == -17.5

    def test_sample_rate(self):
        metric = parse_one(b"x:1|c|@0.25")
        assert metric.sample_rate == pytest.approx(0.25)

    def test_tags_sorted_and_joined(self):
        metric = parse_one(b"x:1|c|#zed,alpha:1")
        assert metric.tags == ["alpha:1", "zed"]
        assert metric.key.joined_tags == "alpha:1,zed"

    def test_tags_and_rate_any_order(self):
        a = parse_one(b"x:1|c|@0.5|#foo:bar")
        b = parse_one(b"x:1|c|#foo:bar|@0.5")
        assert a.key == b.key
        assert a.sample_rate == b.sample_rate == pytest.approx(0.5)

    def test_digest_matches_fnv1a_chain(self):
        metric = parse_one(b"a.b.c:1|c|#x:1")
        h = fnv1a_32(b"a.b.c")
        h = fnv1a_32(b"counter", h)
        h = fnv1a_32(b"x:1", h)
        assert metric.digest == h

    def test_digest_identical_for_same_key(self):
        a = parse_one(b"x:1|c|#t:1,s:2")
        b = parse_one(b"x:99|c|#s:2,t:1")
        assert a.digest == b.digest
        assert a.digest64 == b.digest64


class TestMultiValue:
    def test_multiple_values(self):
        out = parse_all(b"x:1:2:3|ms")
        assert [metric.value for metric in out] == [1.0, 2.0, 3.0]
        assert len({metric.digest for metric in out}) == 1

    def test_multi_value_sets(self):
        out = parse_all(b"x:a:b|s")
        assert [metric.value for metric in out] == ["a", "b"]

    def test_multi_value_shares_rate_and_tags(self):
        out = parse_all(b"x:1:2|h|@0.5|#a:b")
        assert all(metric.sample_rate == pytest.approx(0.5) for metric in out)
        assert all(metric.tags == ["a:b"] for metric in out)

    def test_trailing_empty_segment_ignored(self):
        # parity: "x:1:|c" emits one metric; "x:|c" emits none
        assert [metric.value for metric in parse_all(b"x:1:|c")] == [1.0]
        assert parse_all(b"x:|c") == []
        assert parse_all(b"x:|s") == []

    def test_interior_empty_segment_rejected(self):
        with pytest.raises(ParseError):
            parse_all(b"x::1|c")

    def test_lenient_python_numbers_rejected(self):
        for packet in (b"x: 1|c", b"x:1_0|c", b"x:1|c|@ 0.5", b"x:1 |c"):
            with pytest.raises(ParseError):
                parse_all(packet)


class TestScopes:
    def test_local_only(self):
        metric = parse_one(b"x:1|c|#a:b,veneurlocalonly")
        assert metric.scope == m.MetricScope.LOCAL_ONLY
        assert metric.tags == ["a:b"]

    def test_global_only(self):
        metric = parse_one(b"x:1|c|#veneurglobalonly,a:b")
        assert metric.scope == m.MetricScope.GLOBAL_ONLY
        assert metric.tags == ["a:b"]

    def test_magic_tag_prefix_match(self):
        metric = parse_one(b"x:1|c|#veneurglobalonly:true")
        assert metric.scope == m.MetricScope.GLOBAL_ONLY
        assert metric.tags == []


class TestExtendTags:
    def test_extend_tags_added_and_sorted(self):
        metric = parse_one(b"x:1|c|#m:1", extend_tags=["env:prod"])
        assert metric.tags == ["env:prod", "m:1"]

    def test_extend_tags_override_key(self):
        metric = parse_one(b"x:1|c|#env:dev,m:1", extend_tags=["env:prod"])
        assert metric.tags == ["env:prod", "m:1"]

    def test_extend_tags_on_untagged_metric(self):
        metric = parse_one(b"x:1|c", extend_tags=["env:prod"])
        assert metric.tags == ["env:prod"]


class TestMalformed:
    @pytest.mark.parametrize("packet", [
        b"",
        b"no.pipes.at.all",
        b"no.colon|c",
        b":1|c",                # empty name
        b"x:1||",               # empty type
        b"x:1|q",               # unknown type
        b"x:1|c|",              # trailing empty section
        b"x:1|c||@0.1",         # empty between pipes
        b"x:1|c|@0.5|@0.5",     # duplicate rate
        b"x:1|c|#a|#b",         # duplicate tags
        b"x:1|c|@2",            # rate out of range
        b"x:1|c|@0",            # rate out of range
        b"x:1|c|@nope",         # bad rate
        b"x:nan|g",             # NaN value
        b"x:inf|g",             # Inf value
        b"x:notanumber|g",
        b"x:1|c|%unknown",      # unknown section
        b"x:1:2:bad|h",         # bad value among multi-values
    ])
    def test_rejected(self, packet):
        with pytest.raises(ParseError):
            parse_all(packet)


class TestEvents:
    def test_basic_event(self):
        ev = Parser().parse_event(b"_e{5,4}:title|text")
        assert ev.name == "title"
        assert ev.message == "text"

    def test_full_event(self):
        ev = Parser().parse_event(
            b"_e{5,4}:title|text|d:1136239445|h:h1|k:ak|p:low|s:src|t:error|#a:b,c")
        assert ev.timestamp == 1136239445
        assert ev.tags["vdogstatsd_hostname"] == "h1"
        assert ev.tags["vdogstatsd_ak"] == "ak"
        assert ev.tags["vdogstatsd_pri"] == "low"
        assert ev.tags["vdogstatsd_st"] == "src"
        assert ev.tags["vdogstatsd_at"] == "error"
        assert ev.tags["a"] == "b"
        assert ev.tags["c"] == ""

    def test_newline_unescape(self):
        ev = Parser().parse_event(b"_e{5,8}:title|ab\\ncdef")
        assert ev.message == "ab\ncdef"

    @pytest.mark.parametrize("packet", [
        b"_e{5,4}:titl|text",        # title length mismatch
        b"_e{5,9}:title|text",       # text length mismatch
        b"_e5,4:title|text",         # no braces
        b"_e{0,4}:|text",            # zero title
        b"_e{5,4}:title|text|p:urgent",   # bad priority
        b"_e{5,4}:title|text|t:fatal",    # bad alert
        b"_e{5,4}:title|text|d:1|d:2",    # duplicate section
        b"_e{5,4}:title|text|x:9",        # unknown section
    ])
    def test_rejected(self, packet):
        with pytest.raises(ParseError):
            Parser().parse_event(packet)


class TestServiceChecks:
    def test_basic(self):
        metric = Parser().parse_service_check(b"_sc|svc.check|0")
        assert metric.name == "svc.check"
        assert metric.type == m.STATUS
        assert metric.value == 0

    def test_full(self):
        metric = Parser().parse_service_check(
            b"_sc|svc|2|d:1136239445|h:host9|#q:1|m:bad\\nnews")
        assert metric.value == 2
        assert metric.timestamp == 1136239445
        assert metric.hostname == "host9"
        assert metric.tags == ["q:1"]
        assert metric.message == "bad\nnews"

    @pytest.mark.parametrize("packet", [
        b"_notsc|x|0",
        b"_sc||0",
        b"_sc|x|9",
        b"_sc|x|0|m:msg|h:host",   # section after message
        b"_sc|x|0|d:1|d:2",
    ])
    def test_rejected(self, packet):
        with pytest.raises(ParseError):
            Parser().parse_service_check(packet)


class TestTagging:
    def test_empty_everything(self):
        from veneur_tpu.util.tagging import ExtendTags
        assert ExtendTags().extend([]) == []

    def test_bare_key_override(self):
        from veneur_tpu.util.tagging import ExtendTags
        et = ExtendTags(["region"])
        assert et.extend(["region:us", "a:1"]) == ["a:1", "region"]
