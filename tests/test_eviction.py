"""Idle-key reclamation and cardinality caps: under key churn the
column store's identity state (rows dict, meta list, native intern
table) must stay bounded — the TPU build's answer to the reference's
per-interval sampler reset (reference worker.go:470-489, README.md's
"Expiration" note)."""

from __future__ import annotations

import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.columnstore import CounterTable
from veneur_tpu.core.server import Server
from veneur_tpu.samplers.parser import Parser
from veneur_tpu.sinks.channel import ChannelMetricSink


def mk_metric(name: str, value: float = 1.0):
    out = []
    Parser().parse_metric_fast(b"%s:%f|c" % (name.encode(), value),
                               out.append)
    return out[0]


def cycle(table, idle: int):
    """One flush generation: snapshot + reclaim (what the server does)."""
    table.snapshot_and_reset()
    return table.reclaim_idle(idle)


class TestTableReclaim:
    def test_idle_rows_tombstoned_then_recycled(self):
        t = CounterTable(64)
        t.add(mk_metric("a"))
        t.add(mk_metric("b"))
        assert len(t.rows) == 2
        # interval 1: both touched; intervals 2-3: idle
        cycle(t, idle=2)
        assert cycle(t, idle=2) == []          # idle 1 < 2
        evicted = cycle(t, idle=2)             # idle 2 -> tombstone
        assert sorted(evicted) == [0, 1]
        assert len(t.rows) == 0                # dict entries gone now
        assert t.meta[0] is not None           # meta survives one flush
        cycle(t, idle=2)                       # -> recycled
        assert t.meta[0] is None and t.meta[1] is None
        assert sorted(t._free_rows) == [0, 1]

    def test_active_rows_survive(self):
        t = CounterTable(64)
        for gen in range(6):
            t.add(mk_metric("live"))
            assert cycle(t, idle=2) == []
        assert len(t.rows) == 1

    def test_key_comeback_reuses_free_row(self):
        t = CounterTable(64)
        t.add(mk_metric("x"))
        for _ in range(3):
            cycle(t, idle=2)
        cycle(t, idle=2)
        assert t._free_rows  # x's row recycled
        t.add(mk_metric("y", 7.0))
        assert len(t.rows) == 1
        row = t.rows[next(iter(t.rows))]
        assert t.meta[row].name == "y"
        vals, touched, meta = t.snapshot_and_reset()
        assert touched[row]
        assert vals[row] == 7.0

    def test_straggler_touch_defers_recycle(self):
        t = CounterTable(64)
        t.add(mk_metric("s"))
        cycle(t, idle=1)
        evicted = cycle(t, idle=1)  # tombstoned
        assert evicted == [0]
        # an in-flight native chunk lands on the tombstoned row
        t.add_batch(*_coo([0], [5.0]))
        # next flush: emitted normally, recycle deferred
        vals, touched, meta = t.snapshot_and_reset()
        assert touched[0] and vals[0] == 5.0 and meta[0] is not None
        assert t.reclaim_idle(1) == []
        assert t.meta[0] is not None  # still waiting
        cycle(t, idle=1)
        assert t.meta[0] is None      # quiet interval -> recycled

    def test_straggler_between_snapshot_and_reclaim(self):
        # The narrower window: the straggler chunk lands AFTER the
        # flush's snapshot_and_reset but BEFORE reclaim_idle. touched is
        # set but _last_touched won't be stamped until the NEXT
        # snapshot, so recycle must key off the live touched flag too —
        # otherwise the row is freed while its value sits in the new
        # pending buffer (lost metric, or mis-credit after re-intern).
        t = CounterTable(64)
        t.add(mk_metric("s"))
        cycle(t, idle=1)
        assert cycle(t, idle=1) == [0]        # tombstoned
        t.snapshot_and_reset()                # quiet interval's snapshot
        t.add_batch(*_coo([0], [5.0]))        # straggler in the gap
        assert t.reclaim_idle(1) == []
        assert t.meta[0] is not None          # NOT recycled
        assert not t._free_rows
        vals, touched, meta = t.snapshot_and_reset()
        assert touched[0] and vals[0] == 5.0  # emitted next flush
        cycle(t, idle=1)                      # re-armed: waits one more
        cycle(t, idle=1)
        assert t.meta[0] is None              # quiet -> recycled

    def test_cardinality_cap_drops_and_counts(self):
        t = CounterTable(64, max_rows=4)
        for i in range(10):
            t.add(mk_metric(f"k{i}"))
        assert len(t.rows) == 4
        assert t.keys_dropped == 6
        vals, touched, meta = t.snapshot_and_reset()
        assert int(touched.sum()) == 4


def _coo(rows, vals):
    import numpy as np
    return (np.asarray(rows, np.int32), np.asarray(vals, np.float32),
            np.ones(len(rows), np.float32))


class TestServerChurnBounded:
    def test_churn_keeps_identity_state_bounded(self):
        cfg = Config()
        cfg.interval = 10.0
        cfg.tpu.idle_key_intervals = 2
        cfg.tpu.counter_capacity = 4096
        cfg.apply_defaults()
        ch = ChannelMetricSink()
        server = Server(cfg, extra_metric_sinks=[ch])
        native_on = server._ingester is not None
        # CHURN_KEYS=1000000 runs the full 1M-unique-key soak (minutes);
        # the default keeps CI fast while exercising the same mechanism
        import os
        total = int(os.environ.get("CHURN_KEYS", "3600"))
        waves = 12
        per_wave = max(1, total // waves)
        for wave in range(waves):
            batch = b"\n".join(
                b"churn.w%d.k%d:1|c" % (wave, i) for i in range(per_wave))
            server.handle_packet_batch([batch])
            server.flush()  # snapshot + reclaim
        t = server.store.counters
        # steady state: at most (idle + tombstone-lag + current) waves of
        # identity, never the full churn history
        bound = per_wave * 5
        assert len(t.rows) <= bound, len(t.rows)
        live_meta = sum(1 for mm in t.meta if mm is not None)
        assert live_meta <= bound, live_meta
        if native_on:
            assert server._ingester.interned_keys <= bound
        # the full history DID pass through (waves x per_wave keys)
        assert t._generation >= waves

    def test_evicted_key_returns_through_the_pump(self):
        """The full lifecycle over real UDP: a key interned via the pump
        slow path is evicted (native mapping erased, row recycled), then
        returns — it must re-intern cleanly and aggregate correctly,
        and the engine must shrink at eviction."""
        import socket
        import time

        cfg = Config()
        cfg.interval = 10.0
        cfg.tpu.idle_key_intervals = 1
        cfg.statsd_listen_addresses = ["udp://127.0.0.1:0"]
        cfg.apply_defaults()
        ch = ChannelMetricSink()
        server = Server(cfg, extra_metric_sinks=[ch])
        if server._ingester is None:
            pytest.skip("native unavailable")
        # determinism: a flush self-span's 1% ssf.names_unique roll
        # would make an idle interval's flush non-empty, desyncing the
        # wait_flush consumer and padding store.processed (the pattern
        # test_stress pins the same way)
        server.metric_extraction._uniqueness_rate = 0.0
        server.start()
        try:
            addr = server.local_addr("udp")
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

            def send_and_wait(count, want_processed):
                for _ in range(count):
                    sock.sendto(b"cycle.key:2|c", addr)
                deadline = time.time() + 10
                while (server.store.processed < want_processed
                       and time.time() < deadline):
                    time.sleep(0.05)

            send_and_wait(10, 10)
            server.flush()
            got = {m.name: m.value for m in ch.wait_flush(timeout=5)}
            assert got["cycle.key"] == 20.0
            engine_size = server._ingester.interned_keys
            assert engine_size >= 1
            # idle flushes: tombstone (engine erase) then recycle
            server.flush()
            server.flush()
            assert server._ingester.interned_keys < engine_size
            assert server.store.counters._free_rows  # recycled, not just
            # tombstoned (dict entries empty either way)
            # the key returns: slow path re-interns and re-registers
            send_and_wait(5, 15)
            server.flush()
            got = {}
            for m in ch.wait_flush(timeout=5):
                got[m.name] = m.value
            assert got["cycle.key"] == 10.0
            # and it is native again (registered in the engine)
            assert server._ingester.interned_keys >= 1
        finally:
            try:
                sock.close()
            except Exception:
                pass
            server.shutdown()

    def test_recycled_rows_emit_correct_values(self):
        """Row recycling must never cross-credit: a new key taking a
        recycled row id emits under its own name with its own value."""
        cfg = Config()
        cfg.interval = 10.0
        cfg.tpu.idle_key_intervals = 1
        cfg.apply_defaults()
        ch = ChannelMetricSink()
        server = Server(cfg, extra_metric_sinks=[ch])
        server.handle_metric_packet(b"old.key:3|c")
        server.flush()
        ch.wait_flush()
        for _ in range(3):  # old.key idles out and recycles
            server.flush()
        server.handle_metric_packet(b"new.key:9|c")
        server.flush()
        got = {m.name: m.value for m in ch.wait_flush()}
        assert got == {"new.key": 9.0}
