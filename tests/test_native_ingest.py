"""Parity tests: the native (C++) batch ingest path must be observably
identical to the per-packet Python parser path — same aggregated state,
same stats counters — across the DogStatsD grammar, including the lines
the native parser defers (events, service checks, malformed packets,
non-ASCII set members, unknown keys).
"""

from __future__ import annotations

import random

import pytest

from veneur_tpu import native
from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native parser unavailable: {native.unavailable_reason()}")


def make_server(disable_native: bool):
    cfg = Config()
    cfg.interval = 10.0
    cfg.tpu.disable_native_parser = disable_native
    cfg.apply_defaults()
    ch = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[ch]), ch


def flush_rows(server, ch):
    server.flush()
    return sorted(
        (m.name, m.type.name, round(float(m.value), 4), tuple(m.tags))
        for m in ch.wait_flush())


def _settle_span_workers(server, timeout=15.0, settle=0.25):
    """Wait until the span workers have fully drained AND applied their
    extractions. `span_chan.empty()` alone races the in-flight worker
    iteration (the span was taken off the channel but its metrics not
    yet applied — the load-dependent flake); an empty channel plus a
    `store.processed` count that has been stable for `settle` seconds
    is a deterministic quiesce under any scheduler load."""
    import time
    deadline = time.time() + timeout
    stable_since = time.time()
    last = server.store.processed
    while time.time() < deadline:
        if not server.span_chan.empty():
            stable_since = time.time()
            time.sleep(0.02)
            continue
        cur = server.store.processed
        if cur != last:
            last = cur
            stable_since = time.time()
        elif time.time() - stable_since >= settle:
            return
        time.sleep(0.02)


def run_both(datagram_batches):
    """Feed the same batches through native and Python servers; return
    ((metrics, stats), (metrics, stats))."""
    out = []
    for disable in (False, True):
        server, ch = make_server(disable)
        if not disable:
            assert server._ingester is not None
        for batch in datagram_batches:
            server.handle_packet_batch(batch)
        rows = flush_rows(server, ch)
        out.append((rows, dict(server.stats)))
    return out


CORPUS = [
    b"c1:5|c|#a:b",
    b"c1:3|c|#a:b",
    b"c1:2|c|@0.5|#a:b",
    b"g1:2.5|g",
    b"g1:7|g",  # last write wins
    b"t1:1:2:3:4|ms|@0.5|#x:y",
    b"h1:0.25|h",
    b"d1:9|d",  # distribution -> histogram
    b"s1:u1|s\ns1:u2|s\ns1:u1|s",
    b"bad packet",
    b"nopipe:1",
    b"novalue|c",
    b":1|c",
    b"x:|c",           # empty value chunk: no samples, no error
    b"x:1:|c",         # trailing empty segment ignored
    b"x::1|c",         # empty inner segment: error
    b"dup:1|c|@0.5|@0.5",
    b"dup2:1|c|#a|#b",
    b"weird:1e999|c",  # overflow -> error
    b"tiny:1e-999|g",  # underflow -> 0.0, fine
    b"neg:-12.5|g",
    b"plus:+3|c",
    b"exp:2.5e2|ms",
    b"dot:.5|g",
    b"dotted:5.|g",
    b"under:1_0|c",    # underscores rejected
    b"space: 1|c",     # whitespace rejected
    b"nan:nan|g",
    b"inf:inf|g",
    b"hex:0x10|c",
    b"_sc|check|1|m:oops",
    b"_sc|check|9",
    b"_e{5,4}:title|text",
    b"_e{2,2}:ab|cd|t:error",
    b"_scx:1|c",       # _sc prefix but not a service check -> error path
    b"_metric:1|c",    # leading underscore, ordinary metric
    b"glob:1|c|#veneurglobalonly",
    b"loc:1|ms|#veneurlocalonly,env:x",
    b"setnonascii:caf\xc3\xa9|s",   # non-ASCII member defers to Python
    b"s1:\xff\xfe|s",               # invalid UTF-8 member
    b"multi:1:2:3|c|#m:n",
    b"rate0:1|c|@0",
    b"rate2:1|c|@2",
]


class TestNativeParity:
    def test_corpus_single_pass(self):
        (nat, nat_stats), (py, py_stats) = run_both([CORPUS])
        assert nat == py
        assert nat_stats == py_stats

    def test_corpus_repeated_passes(self):
        # second pass exercises the registered-key native fast path
        (nat, nat_stats), (py, py_stats) = run_both([CORPUS, CORPUS, CORPUS])
        assert nat == py
        assert nat_stats == py_stats

    def test_randomized_traffic(self):
        rng = random.Random(1234)
        names = [f"m{i}" for i in range(50)]
        batches = []
        for _ in range(5):
            batch = []
            for _ in range(200):
                name = rng.choice(names)
                kind = rng.choice([b"c", b"g", b"ms", b"h", b"s"])
                tags = rng.choice([b"", b"|#a:b", b"|#a:b,c:d",
                                   b"|#veneurglobalonly,x:y"])
                rate = rng.choice([b"", b"|@0.5", b"|@0.1"])
                if kind == b"s":
                    val = f"user{rng.randrange(100)}".encode()
                else:
                    val = f"{rng.uniform(-100, 100):.4f}".encode()
                batch.append(b"%s:%s|%s%s%s" %
                             (name.encode(), val, kind, rate, tags))
            batches.append([b"\n".join(batch[i:i + 25])
                            for i in range(0, len(batch), 25)])
        (nat, nat_stats), (py, py_stats) = run_both(batches)
        assert nat == py
        assert nat_stats == py_stats

    def test_oversized_datagram_dropped(self):
        server, ch = make_server(False)
        big = b"x:1|c\n" * 2000  # > metric_max_length
        server.handle_packet_batch([big, b"ok:1|c"])
        assert server.stats["parse_errors"] == 1
        rows = flush_rows(server, ch)
        assert [r[0] for r in rows] == ["ok"]

    def test_interning_registers_keys(self):
        server, _ = make_server(False)
        server.handle_packet_batch([b"a:1|c\nb:2|g\nc:3|ms\nd:x|s"])
        assert server._ingester.interned_keys == 4
        # second pass: no unknown lines -> counts all native
        before = server.stats["packets_received"]
        server.handle_packet_batch([b"a:1|c\nb:2|g\nc:3|ms\nd:x|s"])
        assert server.stats["packets_received"] == before + 4


class TestNativeParser:
    def test_hll_hash_parity(self):
        from veneur_tpu.ops import hll_ref
        parser = native.NativeParser()
        parser.register(b"s|s", native.FAM_SET, 0, 1.0)
        members = [b"a", b"user42", b"x" * 100]
        res = parser.parse(b"\n".join(b"s:%s|s" % mm for mm in members))
        for i, member in enumerate(members):
            idx, rho = hll_ref.pos_val(hll_ref.hash_member(member))
            assert res.s_idx[i] == idx, member
            assert res.s_rho[i] == rho, member

    def test_multivalue_and_rates(self):
        parser = native.NativeParser()
        parser.register(b"t|ms|@0.25|#x:y", native.FAM_HISTO, 3, 0.25)
        res = parser.parse(b"t:1:2:3|ms|@0.25|#x:y")
        assert list(res.h_rows) == [3, 3, 3]
        assert list(res.h_vals) == [1.0, 2.0, 3.0]
        assert list(res.h_wts) == [4.0, 4.0, 4.0]
        assert res.samples == 3

    def test_unknown_keys_deferred(self):
        parser = native.NativeParser()
        res = parser.parse(b"a:1|c\nb:2|g")
        assert res.lines == 2
        assert res.samples == 0
        assert res.unknown == [b"a:1|c", b"b:2|g"]


class TestPump:
    """The C++-resident ingest pump: reader threads own the whole
    socket->parse->accumulate loop; Python only dispatches sealed chunks.
    Parity oracle: a Python-path server fed the same lines in-process."""

    def _udp_server(self, **overrides):
        cfg = Config()
        cfg.interval = 10.0
        cfg.statsd_listen_addresses = ["udp://127.0.0.1:0"]
        for k, v in overrides.items():
            setattr(cfg, k, v)
        cfg.apply_defaults()
        ch = ChannelMetricSink()
        server = Server(cfg, extra_metric_sinks=[ch])
        server.start()
        return server, ch

    def _send_all(self, addr, lines):
        import socket as socketlib
        with socketlib.socket(socketlib.AF_INET,
                              socketlib.SOCK_DGRAM) as s:
            for line in lines:
                s.sendto(line, addr)

    def _wait_processed(self, server, want, timeout=10.0):
        import time
        deadline = time.time() + timeout
        while time.time() < deadline:
            if server.store.processed >= want:
                return
            time.sleep(0.05)

    def test_pump_udp_parity_with_python_path(self):
        # metric lines only: error/event lines over UDP are counted the
        # same way, but this asserts the aggregated values match exactly
        lines = [line for line in CORPUS
                 if b"\n" not in line] * 3
        server, ch = self._udp_server()
        assert server._listeners[0].pump is not None, "pump did not start"
        try:
            self._send_all(server.local_addr("udp"), lines)
            oracle, oracle_ch = make_server(True)
            for line in lines:
                oracle.handle_metric_packet(line)
            want = oracle.store.processed
            self._wait_processed(server, want)
            got = flush_rows(server, ch)
            expected = flush_rows(oracle, oracle_ch)
            assert got == expected
        finally:
            server.shutdown()

    def test_pump_gauge_last_write_wins_across_chunks(self):
        import time
        server, ch = self._udp_server()
        try:
            addr = server.local_addr("udp")
            # groups separated by > seal_age_ms (100ms) land in separate
            # chunks, so this exercises cross-chunk FIFO ordering, not
            # just the within-chunk line-index sort
            sent = 0
            for group in range(3):
                vals = list(range(group * 50, group * 50 + 50))
                self._send_all(addr, [b"lww.g:%d|g" % v for v in vals])
                sent += len(vals)
                self._wait_processed(server, sent)
                time.sleep(0.15)
            got = {r[0]: r[2] for r in flush_rows(server, ch)}
            assert got["lww.g"] == 149.0
        finally:
            server.shutdown()

    def test_pump_shutdown_drains_inflight(self):
        import time
        server, ch = self._udp_server(flush_on_shutdown=True)
        try:
            addr = server.local_addr("udp")
            self._send_all(addr, [b"drain.c:1|c"] * 200)
            time.sleep(0.3)  # reach the kernel buffer / pump chunks
        finally:
            server.shutdown()
        # shutdown closed listeners first, drained the pump, THEN flushed
        got = {m.name: m.value for m in ch.wait_flush(timeout=5)}
        assert got.get("drain.c") == 200.0

    def test_pump_disable_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("VENEUR_TPU_DISABLE_PUMP", "1")
        server, ch = self._udp_server()
        try:
            assert server._listeners[0].pump is None
            self._send_all(server.local_addr("udp"), [b"fb.c:2|c"] * 10)
            self._wait_processed(server, 10)
            got = {r[0]: r[2] for r in flush_rows(server, ch)}
            assert got["fb.c"] == 20.0
        finally:
            server.shutdown()


class TestSsfNative:
    """The native SSF decode path (C++ span decode + metric extraction)
    must be observably identical to the per-packet Python path."""

    def _spans(self):
        from veneur_tpu import ssf
        packets = []
        for i in range(40):
            span = ssf.SSFSpan(
                id=i + 1, trace_id=(i % 7) + 1, name=f"op{i % 5}",
                service="parity-svc", start_timestamp=100 + i,
                end_timestamp=200 + i, indicator=(i % 3 == 0))
            span.metrics.append(ssf.count(
                f"ssfp.c{i % 4}", 2, {"env": "test", "shard": str(i % 2)}))
            span.metrics.append(ssf.gauge(f"ssfp.g{i % 4}", i * 1.5))
            t = ssf.timing(f"ssfp.t{i % 4}", 0.001 * i, 1e-3)
            t.sample_rate = 0.5
            span.metrics.append(t)
            span.metrics.append(ssf.set_sample(
                "ssfp.users", f"user{i}", {"veneurglobalonly": "true"}))
            if i % 10 == 0:
                span.metrics.append(ssf.status(
                    "ssfp.check", ssf.WARNING, "degraded"))
            if i % 11 == 0:
                span.metrics.append(ssf.set_sample("ssfp.non", "café"))
            packets.append(span.SerializeToString())
        packets.append(b"\x07garbage\xff\xff")  # undecodable
        return packets

    def _run(self, packets, disable_native: bool, repeats: int = 2):
        server, ch = make_server(disable_native)
        # uniqueness must be deterministic across paths for the oracle
        server.metric_extraction._uniqueness_rate = 1.0
        server.start()  # the Python path extracts in the span workers
        try:
            for _ in range(repeats):
                if disable_native or server._ingester is None:
                    for p in packets:
                        server.handle_ssf_packet(p)
                else:
                    server.handle_ssf_batch(packets)
            _settle_span_workers(server)
            rows = flush_rows(server, ch)
            return rows, dict(server.stats), server
        finally:
            server.shutdown()

    def test_ssf_batch_parity_with_python_path(self):
        packets = self._spans()
        nat_rows, nat_stats, nat_srv = self._run(packets, False)
        py_rows, py_stats, _ = self._run(packets, True)
        assert nat_rows == py_rows
        assert nat_stats == py_stats

    def test_second_pass_runs_native(self):
        packets = self._spans()
        server, ch = make_server(False)
        server.metric_extraction._uniqueness_rate = 0.0
        server.handle_ssf_batch(packets)  # interns via slow path
        before = server._ingester.interned_keys
        assert before > 0
        # packet 1 has no STATUS / non-ASCII samples (those defer by
        # design forever); all its samples must now extract natively
        res = server._ingester._parser().parse_ssf(
            packets[1], [0], [len(packets[1])], uniq_rate=0.0)
        assert not res.deferred
        assert res.samples > 0

    def test_name_tag_normalization_parity(self):
        """ParseSSF fills an empty span name from tags["name"]
        (wire.go ParseSSF); the native decoder must agree, since the
        span name feeds valid_trace and the uniqueness set member."""
        from veneur_tpu import ssf
        packets = []
        for i in range(20):
            span = ssf.SSFSpan(
                id=i + 1, trace_id=i + 1, service="tagged-svc",
                start_timestamp=10, end_timestamp=20)
            span.tags["name"] = f"tag-op{i % 3}"  # no span.name set
            span.metrics.append(ssf.count(f"nt.c{i % 3}", 1))
            packets.append(span.SerializeToString())
        nat_rows, nat_stats, _ = self._run(packets, False)
        py_rows, py_stats, _ = self._run(packets, True)
        assert nat_rows == py_rows
        assert nat_stats == py_stats

    def test_indicator_timers_via_batch(self):
        from veneur_tpu import ssf
        cfg = Config()
        cfg.interval = 10.0
        cfg.indicator_span_timer_name = "sli.timer"
        cfg.objective_span_timer_name = "slo.timer"
        cfg.apply_defaults()
        results = []
        for use_batch in (True, False):
            ch = ChannelMetricSink()
            server = Server(cfg, extra_metric_sinks=[ch])
            server.metric_extraction._uniqueness_rate = 0.0
            span = ssf.SSFSpan(
                id=5, trace_id=5, name="ind-op", service="svc",
                start_timestamp=10**9, end_timestamp=2 * 10**9,
                indicator=True)
            packet = span.SerializeToString()
            server.start()
            try:
                if use_batch and server._ingester is not None:
                    server.handle_ssf_batch([packet])
                else:
                    server.handle_ssf_packet(packet)
                _settle_span_workers(server)
                results.append(flush_rows(server, ch))
            finally:
                server.shutdown()
        assert results[0] == results[1]
        names = {r[0] for r in results[0]}
        assert any(n.startswith("sli.timer") for n in names)
        assert any(n.startswith("slo.timer") for n in names)


class TestGarbageFuzz:
    def test_byte_soup_never_crashes_and_parsers_agree(self):
        """Random byte soup (printable garbage, truncated metrics,
        embedded pipes/colons/NULs, invalid UTF-8) must never crash
        either pipeline, and the native batch path must produce exactly
        the same flushed metrics and error counts as the Python path."""
        rng = random.Random(99)
        alphabet = (b"abc:|#@.,0159 \xff\x00\xc3()_-=+"
                    b"gcmsh\n")
        batches = []
        for _ in range(3):
            lines = []
            for _ in range(300):
                n = rng.randrange(1, 40)
                lines.append(bytes(rng.choice(alphabet) for _ in range(n)))
            # mix in near-valid prefixes of real metrics
            for base in (b"ok.metric:1|c|#a:b", b"t:3.5|ms|@0.5"):
                for cut in (3, 7, len(base) - 1, len(base)):
                    lines.append(base[:cut])
            rng.shuffle(lines)
            batches.append([b"\n".join(lines[i:i + 20])
                            for i in range(0, len(lines), 20)])
        (nat, nat_stats), (py, py_stats) = run_both(batches)
        assert nat == py
        assert nat_stats == py_stats
