"""Fault-injection tests: chaos seam behavior, forward retry/carryover
under injected faults, and the lossless-carryover soak the acceptance
criteria pin (20 flush rounds at 30 % forward faults, zero counter
loss)."""

import time

import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink
from veneur_tpu.testing.forwardtest import ForwardTestServer
from veneur_tpu.util import chaos as chaos_mod
from veneur_tpu.util.chaos import Chaos, ChaosError

pytestmark = pytest.mark.chaos


def make_config(**overrides) -> Config:
    cfg = Config()
    cfg.interval = 10.0
    cfg.hostname = "test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


def wait_until(fn, timeout=10.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


class TestChaosPlan:
    def test_disabled_is_noop(self):
        c = Chaos(enabled=False, error_rate=1.0)
        for _ in range(10):
            c.inject("forward_send")
        assert not c.injected_errors

    def test_error_rate_one_always_raises(self):
        c = Chaos(error_rate=1.0, seams=("forward_send",))
        with pytest.raises(ChaosError) as ei:
            c.inject("forward_send")
        assert ei.value.seam == "forward_send"
        assert c.injected_errors["forward_send"] == 1

    def test_seam_filtering(self):
        c = Chaos(error_rate=1.0, seams=("sink_flush",))
        c.inject("forward_send")  # not planted: no-op
        with pytest.raises(ChaosError):
            c.inject("sink_flush")

    def test_seeded_determinism(self):
        def run(seed):
            c = Chaos(error_rate=0.3, seed=seed)
            out = []
            for _ in range(50):
                try:
                    c.inject("forward_send")
                    out.append(0)
                except ChaosError:
                    out.append(1)
            return out

        assert run(7) == run(7)
        assert run(7) != run(8)

    def test_delay_injection(self):
        slept = []
        c = Chaos(delay_rate=1.0, delay=0.123, sleep=slept.append)
        c.inject("sink_flush")
        assert slept == [0.123]
        assert c.injected_delays["sink_flush"] == 1

    def test_from_config(self):
        cfg = make_config(chaos_enabled=True, chaos_error_rate=0.25,
                          chaos_seams=["http_post"], chaos_seed=3)
        c = Chaos.from_config(cfg)
        assert c.error_rate == 0.25 and c.seams == frozenset(["http_post"])
        assert Chaos.from_config(make_config()) is None

    def test_http_post_seam(self):
        chaos_mod.install(Chaos(error_rate=1.0, seams=("http_post",)))
        try:
            from veneur_tpu.util import http as http_mod
            with pytest.raises(ChaosError):
                # the seam fires before any socket is touched
                http_mod.post("http://127.0.0.1:1/never", b"{}")
        finally:
            chaos_mod.install(None)

    def test_telemetry_rows(self):
        c = Chaos(error_rate=1.0, seams=("sink_flush",))
        with pytest.raises(ChaosError):
            c.inject("sink_flush")
        rows = c.telemetry_rows()
        assert ("chaos.injected_errors", "counter", 1.0,
                ["seam:sink_flush"]) in rows


class TestHttpRetry:
    def test_post_with_retry_honors_retry_after(self, monkeypatch):
        from veneur_tpu.util import http as http_mod
        from veneur_tpu.util.resilience import RetryPolicy

        calls = []
        sleeps = []

        def fake_post(url, body, **kwargs):
            calls.append(1)
            if len(calls) < 3:
                raise http_mod.HTTPError(429, b"slow down",
                                         retry_after=0.01)
            return 200, b"ok"

        monkeypatch.setattr(http_mod, "post", fake_post)
        monkeypatch.setattr(http_mod.time, "sleep", sleeps.append)
        status, body = http_mod.post_with_retry(
            "http://x/", b"{}", retry=RetryPolicy(max_attempts=5,
                                                  base_delay=0.001),
            budget=5.0)
        assert status == 200 and len(calls) == 3
        assert all(s >= 0.01 for s in sleeps)

    def test_post_with_retry_structural_fails_fast(self, monkeypatch):
        from veneur_tpu.util import http as http_mod

        calls = []

        def fake_post(url, body, **kwargs):
            calls.append(1)
            raise http_mod.HTTPError(401, b"no auth")

        monkeypatch.setattr(http_mod, "post", fake_post)
        with pytest.raises(http_mod.HTTPError):
            http_mod.post_with_retry("http://x/", b"{}", budget=5.0)
        assert len(calls) == 1

    def test_retryable_classification(self):
        from veneur_tpu.util.http import HTTPError
        assert HTTPError(429).retryable and HTTPError(503).retryable
        assert not HTTPError(400).retryable
        assert not HTTPError(500).retryable


class TestForwardChaos:
    def _counter_sum(self, received, name):
        return sum(p.counter.value for p in received if p.name == name)

    def _run_rounds(self, rounds, error_rate, seed=7, per_round=5):
        """Drive `rounds` flush intervals of counter deltas through a
        local server whose forward seam injects faults; returns (total
        received by the global tier, total sent)."""
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        server = None
        try:
            cfg = make_config(
                forward_address=ft.address,
                chaos_enabled=error_rate > 0,
                chaos_error_rate=error_rate,
                chaos_seams=["forward_send"],
                chaos_seed=seed,
                # retries off: carryover alone must preserve the stream
                forward_retry_max_attempts=1,
                # the soak must never shed or trip the breaker — losses
                # would be legitimate then, and we are pinning zero loss
                carryover_max_intervals=1000,
                circuit_breaker_failure_threshold=10_000,
                # the flow ledger replaces bespoke per-seam counting:
                # strict mode makes ANY unexplained imbalance raise out
                # of flush(), so every interval of the soak is a
                # conservation check
                ledger_strict=True,
                ledger_history=64)
            server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
            server.start()
            sent = 0
            for i in range(rounds):
                delta = per_round + i  # distinct per-interval deltas
                server.handle_metric_packet(
                    b"soak.count:%d|c|#veneurglobalonly" % delta)
                sent += delta
                server.flush()
            # drain: chaos off, remaining carryover must deliver
            if server.chaos is not None:
                server.chaos.enabled = False
            server.flush()
            assert wait_until(
                lambda: server.forward_client.carryover.depth == 0)
            # the gRPC handler delivers asynchronously; settle on a total
            wait_until(
                lambda: self._counter_sum(received, "soak.count") >= sent,
                timeout=5.0)
            # zero unexplained imbalance, end to end: every closed
            # interval of the soak (strict mode already raised on any
            # live breach; this pins the recorded history too)
            for interval in server.ledger.history_imbalances():
                assert all(v == 0.0 for v in interval.values()), interval
            assert all(v == 0.0 for v in
                       server.ledger.imbalance_net.values())
            return self._counter_sum(received, "soak.count"), sent
        finally:
            if server is not None:
                server.shutdown()
            ft.stop()

    def test_forward_fault_then_recovery_is_lossless(self):
        """Fast pin of the acceptance property (5 rounds, 50 % faults):
        every counter delta survives via carryover."""
        got, sent = self._run_rounds(rounds=5, error_rate=0.5)
        assert got == sent

    def test_forward_chaos_increments_error_stats(self):
        got, sent = self._run_rounds(rounds=4, error_rate=1.0, seed=1)
        assert got == sent  # all delivered on the final clean drain

    @pytest.mark.slow
    def test_soak_20_rounds_30pct_faults_zero_counter_loss(self):
        """The acceptance soak: 20 flush rounds at 30 % injected fault
        rate — total counter values received by the global tier equal
        the no-fault run exactly."""
        got_chaos, sent_chaos = self._run_rounds(rounds=20, error_rate=0.3)
        got_clean, sent_clean = self._run_rounds(rounds=20, error_rate=0.0)
        assert sent_chaos == sent_clean
        assert got_clean == sent_clean      # control: no-fault baseline
        assert got_chaos == sent_chaos      # zero loss under 30 % faults
        assert got_chaos == got_clean


class TestForwardBreakerAndCarryoverStats:
    def test_breaker_opens_and_refuses_then_recovers(self):
        """Forward breaker: consecutive failures open it; while open the
        client sheds straight to carryover without dialing; the half-open
        probe closes it and the carried state delivers."""
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.util.resilience import (
            OPEN, Carryover, CircuitBreaker, RetryPolicy)

        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        try:
            chaos = Chaos(error_rate=1.0, seams=("forward_send",))
            client = ForwardClient(
                ft.address, deadline=5.0,
                retry=RetryPolicy(max_attempts=1),
                breaker=CircuitBreaker(failure_threshold=2,
                                       recovery_time=0.05, name="fwd"),
                carryover=Carryover(max_intervals=100),
                chaos=chaos)
            from veneur_tpu.core.columnstore import RowMeta
            from veneur_tpu.core.flusher import ForwardableState
            from veneur_tpu.samplers.metrics import MetricScope

            def _mk_meta(name):
                return RowMeta(name=name, tags=[], joined_tags="",
                               digest32=1, scope=MetricScope.GLOBAL_ONLY,
                               wire_type="counter")

            def one(value):
                return ForwardableState(
                    counters=[(_mk_meta("brk.cnt"), value)])

            assert client.forward(one(1.0)) == 0
            assert client.forward(one(2.0)) == 0
            assert client.breaker.state == OPEN
            assert client.forward(one(3.0)) == 0   # refused, no dial
            assert client.stats["breaker_refused_total"] == 1
            assert client.carryover.depth == 3
            chaos.enabled = False
            time.sleep(0.1)                        # past recovery_time
            assert client.forward(one(4.0)) == 1   # half-open probe wins
            assert client.breaker.state == "closed"
            assert client.carryover.depth == 0
            assert wait_until(lambda: sum(
                p.counter.value for p in received
                if p.name == "brk.cnt") == 10.0)
            client.close()
        finally:
            ft.stop()

    def test_forward_client_stats_in_registry(self):
        """Satellite: ForwardClient.stats surface in /metrics."""
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        server = None
        try:
            cfg = make_config(forward_address=ft.address)
            server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
            server.start()
            server.handle_metric_packet(b"st.c:3|c|#veneurglobalonly")
            server.flush()
            assert wait_until(lambda: len(received) >= 1)
            exposition = server.telemetry.registry.render_prometheus()
            assert "veneur_forward_forwarded_total 1" in exposition
            assert "veneur_forward_errors_send_total 0" in exposition
            assert ('veneur_resilience_breaker_state{target="forward"} 0'
                    in exposition)
            assert "veneur_resilience_carryover_depth 0" in exposition
        finally:
            if server is not None:
                server.shutdown()
            ft.stop()

    def test_chaos_sink_flush_seam_feeds_spill(self):
        """sink_flush seam: an injected fault fails the sink thread, the
        batch spills, and the next clean flush delivers it."""
        sink = ChannelMetricSink()
        cfg = make_config(chaos_enabled=True, chaos_error_rate=1.0,
                          chaos_seams=["sink_flush"], interval=2.0)
        server = Server(cfg, extra_metric_sinks=[sink])
        try:
            server.handle_metric_packet(b"cs.a:1|c")
            server.flush()
            assert server._sink_spill  # injected failure spilled it
            server.chaos.enabled = False
            server.handle_metric_packet(b"cs.b:1|c")
            server.flush()
            names = {m.name for m in sink.drain()}
            assert {"cs.a", "cs.b"} <= names
        finally:
            server.shutdown()
