"""Ops-parity tests: scoped self-metrics, diagnostics, crash handling,
flush self-tracing (reference scopedstatsd/client.go, diagnostics/,
sentry.go)."""

import logging
import queue
import socket
import time

import pytest

from veneur_tpu.util import crash
from veneur_tpu.util.scopedstatsd import (
    TAG_GLOBAL_ONLY, TAG_LOCAL_ONLY, NullClient, ScopedClient,
)
from test_server import generate_config, setup_server


class TestScopedClient:
    def test_scope_tags(self):
        # reference YAML keys (config.go VeneurMetricsScopes): timings
        # scope by the `histogram` entry (scopedstatsd/client.go:91-110)
        packets = []
        client = ScopedClient(
            packet_cb=packets.append,
            scopes={"gauge": "local", "counter": "global",
                    "histogram": "local"},
            additional_tags=["svc:veneur"])
        client.gauge("g", 1.5, tags=["x:y"])
        client.count("c", 2)
        client.timing("t", 0.125)
        assert packets[0] == b"g:1.5|g|#x:y,svc:veneur," + \
            TAG_LOCAL_ONLY.encode()
        assert packets[1] == b"c:2|c|#svc:veneur," + TAG_GLOBAL_ONLY.encode()
        assert packets[2] == b"t:125.000|ms|#svc:veneur," + \
            TAG_LOCAL_ONLY.encode()

    def test_scope_tags_alias_keys(self):
        # the pre-parity key names keep working
        packets = []
        client = ScopedClient(
            packet_cb=packets.append,
            scopes={"count": "global", "timing": "local"})
        client.count("c", 2)
        client.timing("t", 0.125)
        assert packets[0] == b"c:2|c|#" + TAG_GLOBAL_ONLY.encode()
        assert packets[1] == b"t:125.000|ms|#" + TAG_LOCAL_ONLY.encode()

    def test_udp_emission(self):
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5.0)
        port = recv.getsockname()[1]
        client = ScopedClient(address=f"127.0.0.1:{port}")
        client.count("hello", 1)
        data, _ = recv.recvfrom(4096)
        assert data == b"hello:1|c"
        client.close()
        recv.close()

    def test_timer_context(self):
        packets = []
        client = ScopedClient(packet_cb=packets.append)
        with client.timer("op"):
            time.sleep(0.01)
        name, rest = packets[0].split(b":", 1)
        assert name == b"op"
        assert float(rest.split(b"|")[0]) >= 10.0

    def test_null_client(self):
        NullClient().count("x")  # no error, no emission


class TestDiagnostics:
    def test_collect_emits_runtime_gauges(self):
        from veneur_tpu.core.diagnostics import collect
        packets = []
        client = ScopedClient(packet_cb=packets.append)
        collect(client, start_time=time.time() - 5, include_device=False)
        names = {p.split(b":", 1)[0].decode() for p in packets}
        assert {"mem.rss_bytes", "cpu.user_seconds", "threads.count",
                "uptime_ms"} <= names

    def test_loop(self):
        from veneur_tpu.core.diagnostics import DiagnosticsLoop
        packets = []
        loop = DiagnosticsLoop(ScopedClient(packet_cb=packets.append),
                               interval=0.05, include_device=False)
        loop.start()
        time.sleep(0.3)
        loop.stop()
        assert len(packets) >= 4


class TestCrash:
    def teardown_method(self):
        crash.clear_reporters()

    def test_consume_panic_reports_and_reraises(self):
        seen = []
        crash.register_reporter(lambda exc, tb: seen.append((exc, tb)))
        with pytest.raises(ValueError):
            try:
                raise ValueError("boom")
            except ValueError as e:
                crash.consume_panic(e)
        assert "boom" in str(seen[0][0])
        assert "ValueError" in seen[0][1]

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_guarded_thread(self):
        seen = []
        crash.register_reporter(lambda exc, tb: seen.append(exc))

        def body():
            raise RuntimeError("thread died")

        t = crash.spawn_guarded(body, name="t")
        t.join(timeout=5)
        assert seen and "thread died" in str(seen[0])

    def test_logging_hook(self):
        seen = []
        crash.register_reporter(lambda exc, tb: seen.append(tb))
        log = logging.getLogger("test.crash.hook")
        handler = crash.ReportingHandler()
        log.addHandler(handler)
        try:
            log.error("an error happened")
            log.info("not reported")
        finally:
            log.removeHandler(handler)
        assert len(seen) == 1
        assert "an error happened" in seen[0]


class TestSelfTelemetry:
    def test_internal_stats_loop_back(self):
        server, observer = setup_server(stats_address="internal")
        server.handle_metric_packet(b"user.metric:1|c")
        server.flush()
        observer.wait_flush()
        # the first flush emitted self-metrics into the store; flush again
        server.flush()
        names = {m.name for m in observer.wait_flush()}
        assert "flush.total_duration_ns" in names
        assert "flush.metrics_total" in names
        server.shutdown()

    def test_flush_emits_self_span(self):
        from veneur_tpu.sinks.channel import ChannelSpanSink
        span_sink = ChannelSpanSink()
        server, observer = setup_server()
        server.span_sinks.insert(0, span_sink)
        server.start()
        try:
            server.handle_metric_packet(b"m:1|c")
            server.flush()
            deadline = time.time() + 5
            while time.time() < deadline:
                if any(s.name == "flush" for s in span_sink.spans):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("flush span never reached span sinks")
        finally:
            server.shutdown()

    def test_stats_address_udp(self):
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5.0)
        port = recv.getsockname()[1]
        server, observer = setup_server(
            stats_address=f"127.0.0.1:{port}")
        server.handle_metric_packet(b"m:1|c")
        server.flush()
        data, _ = recv.recvfrom(4096)
        assert b"|" in data  # statsd-shaped self-metric arrived
        server.shutdown()
        recv.close()
