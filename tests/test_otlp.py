"""OTLP ingest plane: wire decoding (protobuf + JSON), the shared
cumulative->delta semantics (counter-reset 0-clamp pin, shared with the
OpenMetrics source), exponential-histogram -> llhist mapping, and the
acceptance round trip: an OTLP/HTTP POST of ExponentialHistogram points
flushes to correct Prometheus `_bucket`/`_sum`/`_count` output."""

from __future__ import annotations

import json
import re
import struct
import threading
import urllib.request

import numpy as np
import pytest

from veneur_tpu.samplers import metrics as m
from veneur_tpu.sources import CumulativeDeltaCache
from veneur_tpu.sources.otlp import (
    OTLPSource, TEMPORALITY_CUMULATIVE, TEMPORALITY_DELTA, _EHistCache,
    parse_export_json, parse_export_request)

pytestmark = pytest.mark.otlp


# -- tiny protobuf writer (mirror of the source's generic reader) ----------

def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _ld(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _vi(field: int, value: int) -> bytes:
    return _varint(field << 3) + _varint(value)


def _f64(field: int, value: float) -> bytes:
    return bytes([(field << 3) | 1]) + struct.pack("<d", value)


def _fx64(field: int, value: int) -> bytes:
    return bytes([(field << 3) | 1]) + struct.pack("<Q", value)


def _zz(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _attr(key: str, value: str) -> bytes:
    return _ld(1, _ld(1, key.encode()) + _ld(2, _ld(1, value.encode())))


def _np_attr(key: str, value: str) -> bytes:
    """KeyValue serialized as a NumberDataPoint.attributes entry."""
    kv = _ld(1, key.encode()) + _ld(2, _ld(1, value.encode()))
    return _ld(7, kv)


def _metric_gauge(name: str, points) -> bytes:
    g = b"".join(_ld(1, p) for p in points)
    return _ld(1, name.encode()) + _ld(5, g)


def _metric_sum(name: str, points, temporality: int,
                monotonic: bool) -> bytes:
    s = b"".join(_ld(1, p) for p in points)
    s += _vi(2, temporality) + _vi(3, 1 if monotonic else 0)
    return _ld(1, name.encode()) + _ld(7, s)


def _buckets(offset: int, counts) -> bytes:
    return _vi(1, _zz(offset)) + _ld(2, b"".join(_varint(c) for c in counts))


def _ehist_point(scale: int, zero_count: int, pos, neg,
                 attrs: bytes = b"") -> bytes:
    out = attrs
    out += _vi(6, _zz(scale))
    out += _fx64(7, zero_count)
    out += _ld(8, _buckets(*pos))
    if neg is not None:
        out += _ld(9, _buckets(*neg))
    return out


def _ehp_attr(key: str, value: str) -> bytes:
    kv = _ld(1, key.encode()) + _ld(2, _ld(1, value.encode()))
    return _ld(1, kv)  # attributes are field 1 on EHDP


def _metric_ehist(name: str, points, temporality: int) -> bytes:
    eh = b"".join(_ld(1, p) for p in points) + _vi(2, temporality)
    return _ld(1, name.encode()) + _ld(10, eh)


def _request(*metrics: bytes) -> bytes:
    sm = b"".join(_ld(2, mm) for mm in metrics)
    return _ld(1, _ld(2, sm))


# -- shared delta semantics (the counter-reset pin) ------------------------


class TestCumulativeDeltaCache:
    def test_first_observation_primes(self):
        c = CumulativeDeltaCache()
        assert c.delta(("x",), 100.0) is None

    def test_growth_emits_delta(self):
        c = CumulativeDeltaCache()
        c.delta(("x",), 100.0)
        assert c.delta(("x",), 130.0) == 30.0
        assert c.delta(("x",), 130.0) == 0.0

    def test_reset_emits_new_count_never_negative(self):
        """The counter-reset pin: a restarted exporter's new cumulative
        count is real traffic (emit it), and a broken exporter that goes
        NEGATIVE must clamp to 0 — never a negative spike."""
        c = CumulativeDeltaCache()
        c.delta(("x",), 100.0)
        assert c.delta(("x",), 7.0) == 7.0      # reset: new count
        assert c.delta(("x",), -50.0) == 0.0    # broken: 0-clamped
        # and the negative value primes, so recovery is a plain delta
        assert c.delta(("x",), -20.0) == 30.0

    def test_bounded_cache_clears_wholesale(self):
        c = CumulativeDeltaCache(max_series=2)
        c.delta(("a",), 1.0)
        c.delta(("b",), 1.0)
        c.delta(("c",), 1.0)  # clears, then primes c
        assert c.delta(("c",), 4.0) == 3.0
        assert c.delta(("a",), 9.0) is None  # was evicted, re-primes

    def test_openmetrics_source_shares_the_semantics(self):
        from veneur_tpu.sources.openmetrics import OpenMetricsSource
        src = OpenMetricsSource("om", url="http://unused", scrape_interval=60)
        assert src._counter_delta("n", ["a:b"], 10.0) is None
        assert src._counter_delta("n", ["a:b"], 25.0) == 15.0
        assert src._counter_delta("n", ["a:b"], 3.0) == 3.0   # reset
        assert src._counter_delta("n", ["a:b"], -1.0) == 0.0  # 0-clamp


# -- wire decoding ----------------------------------------------------------


class TestProtoDecoding:
    def test_gauge_sum_ehist(self):
        body = _request(
            _metric_gauge("cpu", [_np_attr("core", "0") + _f64(4, 0.5)]),
            _metric_sum("reqs", [struct.pack("<B", (6 << 3) | 1)
                                 + struct.pack("<q", 42)],
                        TEMPORALITY_CUMULATIVE, True),
            _metric_ehist("lat", [_ehist_point(3, 2, (10, [5, 0, 3]),
                                               (-2, [1]))],
                          TEMPORALITY_DELTA),
        )
        points = list(parse_export_request(body))
        kinds = [p[0] for p in points]
        assert kinds == ["gauge", "sum", "ehist"]
        g = points[0]
        assert g[1] == "cpu" and g[2] == {"core": "0"} and g[3] == 0.5
        s = points[1]
        assert s[3] == 42.0 and s[4] == TEMPORALITY_CUMULATIVE and s[5]
        _, name, pt, temp = points[2]
        assert name == "lat" and temp == TEMPORALITY_DELTA
        assert pt["scale"] == 3 and pt["zero_count"] == 2
        assert pt["pos"] == (10, [5, 0, 3])
        assert pt["neg"] == (-2, [1])

    def test_unsupported_kinds_reported(self):
        hist = _ld(1, b"h") + _ld(9, b"")
        summary = _ld(1, b"s") + _ld(11, b"")
        points = list(parse_export_request(_request(hist, summary)))
        assert points == [("unsupported", "histogram"),
                          ("unsupported", "summary")]

    def test_json_equivalence(self):
        doc = {"resourceMetrics": [{"scopeMetrics": [{"metrics": [
            {"name": "cpu", "gauge": {"dataPoints": [
                {"asDouble": 0.5,
                 "attributes": [{"key": "core",
                                 "value": {"intValue": "0"}}]}]}},
            {"name": "reqs", "sum": {
                "isMonotonic": True,
                "aggregationTemporality":
                    "AGGREGATION_TEMPORALITY_CUMULATIVE",
                "dataPoints": [{"asInt": "42"}]}},
            {"name": "lat", "exponentialHistogram": {
                "aggregationTemporality": 1,
                "dataPoints": [{"scale": 3, "zeroCount": "2",
                                "positive": {"offset": 10,
                                             "bucketCounts":
                                                 ["5", "0", "3"]},
                                "negative": {"offset": -2,
                                             "bucketCounts": ["1"]}}]}},
        ]}]}]}
        points = list(parse_export_json(json.dumps(doc).encode()))
        assert [p[0] for p in points] == ["gauge", "sum", "ehist"]
        assert points[0][2] == {"core": "0"} and points[0][3] == 0.5
        assert points[1][3] == 42.0
        pt = points[2][2]
        assert pt["pos"] == (10, [5, 0, 3]) and pt["zero_count"] == 2


class TestEHistCache:
    def test_cumulative_to_delta(self):
        c = _EHistCache()
        p1 = {"attrs": {}, "scale": 3, "zero_count": 2,
              "pos": (10, [5, 3]), "neg": (0, [])}
        assert c.delta(("k",), p1) is p1  # primes: current stands
        p2 = {"attrs": {}, "scale": 3, "zero_count": 5,
              "pos": (10, [9, 3]), "neg": (0, [])}
        d = c.delta(("k",), p2)
        assert d["zero_count"] == 3 and d["pos"] == (10, [4, 0])

    def test_reset_and_upscale_stand_as_is(self):
        c = _EHistCache()
        p1 = {"attrs": {}, "scale": 3, "zero_count": 2,
              "pos": (10, [5, 3]), "neg": (0, [])}
        c.delta(("k",), p1)
        shrunk = {"attrs": {}, "scale": 3, "zero_count": 2,
                  "pos": (10, [1, 3]), "neg": (0, [])}
        assert c.delta(("k",), shrunk) is shrunk  # bucket shrank: reset
        upscaled = {"attrs": {}, "scale": 5, "zero_count": 9,
                    "pos": (40, [1]), "neg": (0, [])}
        assert c.delta(("k",), upscaled) is upscaled  # finer = restart

    def test_downscale_is_not_a_reset(self):
        """An SDK downscale (coarser bins as the range grows) preserves
        the cumulative history: the previous point re-buckets onto the
        new scale and the delta excludes everything already counted —
        treating it as a reset would double-ingest the history."""
        c = _EHistCache()
        p1 = {"attrs": {}, "scale": 3, "zero_count": 2,
              "pos": (10, [5, 3, 0, 7]), "neg": (0, [])}
        c.delta(("k",), p1)
        # scale 3 -> 1 (d=2): prev indexes 10..13 -> coarse 2 (10,11)
        # and 3 (12,13): [8, 7]. New cumulative adds 4 to coarse bin 2
        # and a new coarse bin 4 with 9.
        p2 = {"attrs": {}, "scale": 1, "zero_count": 2,
              "pos": (2, [12, 7, 9]), "neg": (0, [])}
        d = c.delta(("k",), p2)
        assert d["zero_count"] == 0
        assert d["pos"] == (2, [4, 0, 9])

    def test_downscale_rebucket_math(self):
        # negative offsets floor-shift: indexes -3,-2,-1,0 at d=1 map
        # to coarse -2,-1,-1,0
        off, counts = _EHistCache._downscale((-3, [1, 2, 3, 4]), 1)
        assert off == -2
        assert counts == [1, 2 + 3, 4]
        assert _EHistCache._downscale((5, []), 2) == (0, [])
        assert _EHistCache._downscale((5, [7]), 0) == (5, [7])


# -- the HTTP plane ---------------------------------------------------------


class TestWeightChunking:
    def test_counts_past_the_rate_floor_chunk(self):
        """A bucket count past 1e9 would be silently capped by the
        columnstore's 1e-9 sample-rate floor; the source must chunk it
        so the total weight survives."""
        src = OTLPSource("chunk", listen_address="127.0.0.1:0")

        class I:
            metrics = []

            def ingest_metric(self, mm):
                self.metrics.append(mm)
        ingest = I()
        src._ingest = ingest
        src._ingest_ehist(
            "big", {"attrs": {}, "scale": 0, "zero_count": 2_500_000_000,
                    "pos": (0, [3]), "neg": (0, [])}, [])
        weights = [round(1 / mm.sample_rate) for mm in ingest.metrics]
        zero_w = [w for mm, w in zip(ingest.metrics, weights)
                  if mm.value == 0.0]
        assert sum(zero_w) == 2_500_000_000
        assert max(weights) <= 10 ** 9
        # every chunk survives the columnstore's rate floor exactly
        assert all(round(1 / max(1 / w, 1e-9)) == w for w in weights)


class CollectingIngest:
    def __init__(self):
        self.metrics = []

    def ingest_metric(self, metric):
        self.metrics.append(metric)

    def by_name(self):
        out = {}
        for mm in self.metrics:
            out.setdefault(mm.name, []).append(mm)
        return out


@pytest.fixture
def otlp_source():
    src = OTLPSource("otlp-test", listen_address="127.0.0.1:0")
    ingest = CollectingIngest()
    t = threading.Thread(target=src.start, args=(ingest,), daemon=True)
    t.start()
    assert src._started.wait(5)
    # serve_forever is up once the socket exists; port is bound in start
    for _ in range(100):
        if src.port:
            break
    yield src, ingest
    src.stop()
    t.join(5)


def _post(src, body, ctype):
    req = urllib.request.Request(
        f"http://127.0.0.1:{src.port}/v1/metrics", data=body,
        headers={"Content-Type": ctype})
    return urllib.request.urlopen(req, timeout=5)


class TestHTTPPlane:
    def test_protobuf_post(self, otlp_source):
        src, ingest = otlp_source
        body = _request(
            _metric_gauge("otlp.cpu", [_np_attr("core", "1")
                                       + _f64(4, 0.25)]),
            _metric_ehist("otlp.lat",
                          [_ehist_point(3, 1, (0, [4]), None)],
                          TEMPORALITY_DELTA))
        resp = _post(src, body, "application/x-protobuf")
        assert resp.status == 200
        got = ingest.by_name()
        assert got["otlp.cpu"][0].value == 0.25
        assert got["otlp.cpu"][0].key.type == m.GAUGE
        assert "core:1" in got["otlp.cpu"][0].tags
        lat = got["otlp.lat"]
        # zero bucket (count 1) + one positive bucket (count 4)
        assert {mm.key.type for mm in lat} == {m.LLHIST}
        weights = sorted(round(1 / mm.sample_rate) for mm in lat)
        assert weights == [1, 4]

    def test_json_post_and_cumulative_sum(self, otlp_source):
        src, ingest = otlp_source
        doc = {"resourceMetrics": [{"scopeMetrics": [{"metrics": [
            {"name": "otlp.reqs", "sum": {
                "isMonotonic": True,
                "aggregationTemporality":
                    "AGGREGATION_TEMPORALITY_CUMULATIVE",
                "dataPoints": [{"asInt": "100"}]}}]}]}]}
        resp = _post(src, json.dumps(doc).encode(), "application/json")
        assert resp.status == 200 and resp.read() == b"{}"
        assert "otlp.reqs" not in ingest.by_name()  # primed
        doc["resourceMetrics"][0]["scopeMetrics"][0]["metrics"][0][
            "sum"]["dataPoints"][0]["asInt"] = "125"
        _post(src, json.dumps(doc).encode(), "application/json")
        got = ingest.by_name()["otlp.reqs"]
        assert got[0].key.type == m.COUNTER and got[0].value == 25.0

    def test_bad_body_is_400(self, otlp_source):
        src, _ = otlp_source
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(src, b"{not json", "application/json")
        assert ei.value.code == 400

    def test_unknown_path_is_404(self, otlp_source):
        src, _ = otlp_source
        req = urllib.request.Request(
            f"http://127.0.0.1:{src.port}/v1/traces", data=b"",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 404

    def _post_encoded(self, src, body, ctype, encoding):
        req = urllib.request.Request(
            f"http://127.0.0.1:{src.port}/v1/metrics", data=body,
            headers={"Content-Type": ctype,
                     "Content-Encoding": encoding})
        return urllib.request.urlopen(req, timeout=5)

    def test_gzip_protobuf_post(self, otlp_source):
        """Real collector peers ship gzip request bodies by default
        (otlphttpexporter `compression: gzip`)."""
        import gzip
        src, ingest = otlp_source
        body = _request(
            _metric_gauge("otlp.gz", [_np_attr("core", "0")
                                      + _f64(4, 0.75)]))
        resp = self._post_encoded(src, gzip.compress(body),
                                  "application/x-protobuf", "gzip")
        assert resp.status == 200
        assert ingest.by_name()["otlp.gz"][0].value == 0.75

    def test_gzip_json_post(self, otlp_source):
        import gzip
        src, ingest = otlp_source
        doc = {"resourceMetrics": [{"scopeMetrics": [{"metrics": [
            {"name": "otlp.gzj", "gauge": {
                "dataPoints": [{"asDouble": 1.25}]}}]}]}]}
        resp = self._post_encoded(src, gzip.compress(
            json.dumps(doc).encode()), "application/json", "gzip")
        assert resp.status == 200
        assert ingest.by_name()["otlp.gzj"][0].value == 1.25

    def test_gzip_bomb_rejected_bounded(self, otlp_source, monkeypatch):
        """The decompressed-size guard fires DURING inflation: a body
        that would expand past the bound answers 400, and the expansion
        never materializes."""
        import gzip
        from veneur_tpu.sources.otlp import OTLPSource
        src, ingest = otlp_source
        monkeypatch.setattr(OTLPSource, "GZIP_MAX_DECOMPRESSED", 4096)
        bomb = gzip.compress(b"\x00" * 1_000_000)  # ~1 KB compressed
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post_encoded(src, bomb, "application/x-protobuf",
                               "gzip")
        assert ei.value.code == 400
        assert not ingest.by_name()

    def test_garbage_gzip_rejected(self, otlp_source):
        src, _ = otlp_source
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post_encoded(src, b"\x1f\x8bnot really gzip",
                               "application/x-protobuf", "gzip")
        assert ei.value.code == 400

    def test_unsupported_encoding_is_415(self, otlp_source):
        src, _ = otlp_source
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post_encoded(src, b"x", "application/x-protobuf",
                               "zstd")
        assert ei.value.code == 415


# -- acceptance: OTLP -> flush -> Prometheus/Cortex ------------------------


class TestAcceptanceRoundTrip:
    def _mk_server(self, extra_sinks):
        from veneur_tpu.config import Config, SourceConfig
        from veneur_tpu.core.server import Server

        cfg = Config()
        cfg.interval = 3600.0
        cfg.statsd_listen_addresses = []
        cfg.sources = [SourceConfig(
            kind="otlp", name="otlp",
            config={"listen_address": "127.0.0.1:0"})]
        cfg.apply_defaults()
        return Server(cfg, extra_metric_sinks=extra_sinks)

    def test_exponential_histogram_to_prometheus(self):
        """THE acceptance pin: an OTLP/HTTP POST of an
        ExponentialHistogram round-trips to correct `_bucket`/`_sum`/
        `_count` Prometheus exposition on flush."""
        from veneur_tpu.sinks.prometheus import PrometheusMetricSink

        prom = PrometheusMetricSink("prom")
        server = self._mk_server([prom])
        server.start()
        try:
            src = server.sources[0]
            assert src._started.wait(5)
            # scale 3, zero_count 2, buckets: idx 10 -> 5 @ 2^(10.5/8),
            # idx 12 -> 3 @ 2^(12.5/8)
            body = _request(_metric_ehist(
                "rpc.latency",
                [_ehist_point(3, 2, (10, [5, 0, 3]), None,
                              attrs=_ehp_attr("svc", "api"))],
                TEMPORALITY_DELTA))
            _post(src, body, "application/x-protobuf")
            server.store.apply_all_pending()
            server.flush()
            expo = prom._exposition
            # count: 2 + 5 + 3
            assert re.search(
                r'rpc_latency_count\{svc="api"\} 10\.0', expo), expo
            assert re.search(
                r'rpc_latency_sum\{svc="api"\} ', expo), expo
            buckets = re.findall(
                r'rpc_latency_bucket\{svc="api",le="([^"]+)"\} ([0-9.]+)',
                expo)
            by_le = dict(buckets)
            assert by_le["+Inf"] == "10.0"
            # zero bucket: le="0" covers the 2 zero samples
            assert by_le["0"] == "2.0"
            # representatives: 2^(10.5/8)=2.48.. and 2^(12.5/8)=2.95..
            # land in llhist bins with upper edges 2.5 and 3.0
            assert by_le["2.5"] == "7.0"
            assert by_le["3"] == "10.0"
            # cumulative over ascending le
            vals = [float(v) for _, v in sorted(
                buckets, key=lambda kv: float(kv[0])
                if kv[0] != "+Inf" else np.inf)]
            assert vals == sorted(vals)
        finally:
            server.shutdown()

    def test_exponential_histogram_to_cortex(self):
        """Same flush through the Cortex remote-write encoder: decoded
        WriteRequest series carry the _bucket/_sum/_count names."""
        from veneur_tpu.sinks.cortex import (CortexMetricSink,
                                             decode_write_request)
        from veneur_tpu.util import http as vhttp

        captured = []
        sink = CortexMetricSink("cortex", url="http://unused.invalid/w",
                                hostname="h")
        server = self._mk_server([sink])
        orig_post = vhttp.post
        vhttp.post = lambda url, body, **kw: captured.append(body) or (200, b"")
        server.start()
        try:
            src = server.sources[0]
            assert src._started.wait(5)
            body = _request(_metric_ehist(
                "rpc.latency", [_ehist_point(3, 0, (10, [5]), None)],
                TEMPORALITY_DELTA))
            _post(src, body, "application/x-protobuf")
            server.store.apply_all_pending()
            server.flush()
            assert captured, "cortex sink posted nothing"
            series = []
            for b in captured:
                series.extend(decode_write_request(vhttp.snappy_decode(b)))
            names = {labels["__name__"] for labels, _v, _t in series}
            assert {"rpc_latency_bucket", "rpc_latency_sum",
                    "rpc_latency_count"} <= names
            bucket_les = {labels["le"]: v for labels, v, _ in series
                          if labels["__name__"] == "rpc_latency_bucket"}
            assert bucket_les["+Inf"] == 5.0
        finally:
            vhttp.post = orig_post
            server.shutdown()


# -- exposition escaping round trip (satellite) -----------------------------


class TestExpositionEscaping:
    def test_label_values_roundtrip(self):
        from veneur_tpu.samplers.metrics import InterMetric, MetricType
        from veneur_tpu.sinks.prometheus import render_exposition
        from veneur_tpu.sources.openmetrics import parse_exposition

        nasty = ['back\\slash', 'quo"te', 'new\nline', 'mix\\"\n\\\\end',
                 'trailing\\']
        metrics = [
            InterMetric(name=f"esc_{i}", timestamp=0, value=float(i),
                        tags=[f"k:{v}"], type=MetricType.GAUGE)
            for i, v in enumerate(nasty)
        ]
        text = render_exposition(metrics)
        assert len(text.splitlines()) == len(nasty)  # \n escaped
        parsed = {name: labels["k"]
                  for _t, name, labels, _v in parse_exposition(text)}
        for i, v in enumerate(nasty):
            assert parsed[f"esc_{i}"] == v, (parsed[f"esc_{i}"], v)
