"""HyperLogLog accuracy tests: scalar reference and batched device kernel.
The p=14 sketch has ~0.8% standard error; we allow 3 sigma."""

import numpy as np
import pytest

from veneur_tpu.ops import batch_hll as bhll
from veneur_tpu.ops.hll_ref import HLL, hash_member, pos_val


class TestScalarHLL:
    @pytest.mark.parametrize("n", [100, 1000, 10000, 100000])
    def test_estimate_accuracy(self, n):
        h = HLL()
        for i in range(n):
            h.insert(b"member-%d" % i)
        assert h.estimate() == pytest.approx(n, rel=0.03)

    def test_duplicates_not_counted(self):
        h = HLL()
        for _ in range(5):
            for i in range(1000):
                h.insert(b"m%d" % i)
        assert h.estimate() == pytest.approx(1000, rel=0.03)

    def test_merge(self):
        a, b = HLL(), HLL()
        for i in range(5000):
            a.insert(b"a%d" % i)
            b.insert(b"b%d" % i)
        a.merge(b)
        assert a.estimate() == pytest.approx(10000, rel=0.03)

    def test_merge_overlapping(self):
        a, b = HLL(), HLL()
        for i in range(5000):
            a.insert(b"x%d" % i)
            b.insert(b"x%d" % i)
        a.merge(b)
        assert a.estimate() == pytest.approx(5000, rel=0.03)

    def test_serialization_roundtrip(self):
        a = HLL()
        for i in range(1234):
            a.insert(b"v%d" % i)
        b = HLL.from_bytes(a.to_bytes())
        assert b.estimate() == a.estimate()

    def test_empty(self):
        assert HLL().estimate() == pytest.approx(0, abs=1)


class TestBatchedHLL:
    def _ingest(self, members_by_row, num_keys, batch=4096):
        regs = bhll.init_state(num_keys)
        coo = []
        for row, members in members_by_row.items():
            for member in members:
                idx, rho = pos_val(hash_member(member))
                coo.append((row, idx, rho))
        for i in range(0, len(coo), batch):
            chunk = coo[i:i + batch]
            pad = batch - len(chunk)
            rows = np.array([c[0] for c in chunk] + [num_keys] * pad, np.int32)
            idxs = np.array([c[1] for c in chunk] + [0] * pad, np.int32)
            rhos = np.array([c[2] for c in chunk] + [0] * pad, np.int32)
            regs = bhll.apply_batch(regs, rows, idxs, rhos)
        return regs

    def test_matches_scalar(self):
        members = [b"user-%d" % i for i in range(20000)]
        regs = self._ingest({0: members, 1: members[:500]}, 2)
        scalar = HLL()
        for member in members:
            scalar.insert(member)
        est = bhll.estimate(regs)
        assert float(est[0]) == pytest.approx(scalar.estimate(), rel=1e-6)
        assert float(est[1]) == pytest.approx(500, rel=0.05)
        # registers must be identical to the scalar sketch
        np.testing.assert_array_equal(np.asarray(regs)[0], scalar.regs)

    def test_empty_row_estimates_zero(self):
        regs = bhll.init_state(2)
        est = bhll.estimate(regs)
        assert float(est[0]) == 0.0

    def test_merge_rows(self):
        a = self._ingest({0: [b"a%d" % i for i in range(3000)]}, 2)
        b_scalar = HLL()
        for i in range(3000):
            b_scalar.insert(b"b%d" % i)
        merged = bhll.merge_rows(
            a, np.array([0], np.int32), b_scalar.regs[None, :])
        est = bhll.estimate(merged)
        assert float(est[0]) == pytest.approx(6000, rel=0.03)

    def test_shard_merge(self):
        a = self._ingest({0: [b"m%d" % i for i in range(4000)]}, 1)
        b = self._ingest({0: [b"m%d" % i for i in range(2000, 6000)]}, 1)
        merged = bhll.merge(a, b)
        est = bhll.estimate(merged)
        assert float(est[0]) == pytest.approx(6000, rel=0.03)


class TestScalarKernels:
    def test_counters(self):
        from veneur_tpu.ops import scalars
        state = scalars.init_counters(4)
        rows = np.array([0, 0, 1, 4, 2], np.int32)  # 4 = padding
        vals = np.array([1.0, 2.0, 5.0, 99.0, 1.0], np.float32)
        rates = np.array([1.0, 0.5, 1.0, 1.0, 0.1], np.float32)
        state = scalars.apply_counters(state, rows, vals, rates)
        assert scalars.counter_values(state).tolist() == [5.0, 5.0, 10.0, 0.0]

    def test_counter_truncation_per_sample(self):
        # parity: each sample contributes trunc(value/rate)
        from veneur_tpu.ops import scalars
        state = scalars.init_counters(1)
        rows = np.array([0, 0], np.int32)
        vals = np.array([1.0, 1.0], np.float32)
        rates = np.array([0.3, 0.3], np.float32)
        state = scalars.apply_counters(state, rows, vals, rates)
        # trunc(3.33)*2, not trunc(6.66)
        assert float(scalars.counter_values(state)[0]) == 6.0

    def test_counter_kahan_precision(self):
        # many small batches must not drift past f32 granularity
        from veneur_tpu.ops import scalars
        state = scalars.init_counters(1)
        rows = np.zeros(1024, np.int32)
        vals = np.full(1024, 33.0, np.float32)
        rates = np.ones(1024, np.float32)
        for _ in range(600):  # 600 * 1024 * 33 = 20,275,200 > 2^24
            state = scalars.apply_counters(state, rows, vals, rates)
        got = float(scalars.counter_values(state)[0])
        assert got == 600 * 1024 * 33.0

    def test_gauges_last_write_wins(self):
        from veneur_tpu.ops import scalars
        state = scalars.init_gauges(3)
        rows = np.array([0, 1, 0, 3], np.int32)
        vals = np.array([1.0, 2.0, 7.0, 99.0], np.float32)
        state = scalars.apply_gauges(state, rows, vals)
        assert state["value"].tolist() == [7.0, 2.0, 0.0]
        assert state["set"].tolist() == [True, True, False]
        # second batch: only row 1 updated
        state = scalars.apply_gauges(
            state, np.array([1], np.int32), np.array([5.0], np.float32))
        assert state["value"].tolist() == [7.0, 5.0, 0.0]


class TestSparseSetTable:
    """Two-tier set representation (reference keeps small HLLs sparse,
    vendor hyperloglog sparse.go): small keys never allocate device
    registers, hot keys promote mid-interval, and both tiers produce
    identical estimates and register rows."""

    def _mk(self, capacity=512, batch_cap=64, promote_samples=0,
            max_dev_slots=0):
        from veneur_tpu.core.columnstore import SetTable
        return SetTable(capacity, batch_cap, sparse=True,
                        promote_samples=promote_samples,
                        max_dev_slots=max_dev_slots)

    def _stub(self, name):
        from veneur_tpu.samplers.parser import Parser
        out = []
        Parser().parse_metric_fast(b"%s:x|s" % name, out.append)
        return out[0]

    def test_small_sets_stay_off_device(self):
        # explicit high threshold: the point here is the sparse tier's
        # estimate/register parity, independent of the promote policy
        import numpy as np
        from veneur_tpu.ops import hll_ref
        table = self._mk(promote_samples=2048)
        members = [b"m%d" % i for i in range(500)]
        rows, idxs, rhos = [], [], []
        stub = self._stub(b"sp.small")
        with table.lock:
            row = table.row_for(stub)
        for m in members:
            i, r = hll_ref.pos_val(hll_ref.hash_member(m))
            rows.append(row); idxs.append(i); rhos.append(r)
        table.add_batch(np.array(rows, np.int32), np.array(idxs, np.int32),
                        np.array(rhos, np.int32))
        table.apply_pending()
        assert table._nslots == 0  # never promoted
        est, regs, touched, _ = table.snapshot_and_reset()
        oracle = hll_ref.HLL()
        for m in members:
            oracle.insert(m)
        assert float(est[row]) == oracle.estimate()
        np.testing.assert_array_equal(regs[row], oracle.regs)

    def test_hot_key_promotes_and_matches_dense(self):
        import numpy as np
        from veneur_tpu.ops import hll_ref
        table = self._mk(batch_cap=256)
        stub = self._stub(b"sp.hot")
        with table.lock:
            row = table.row_for(stub)
        oracle = hll_ref.HLL()
        rng = np.random.default_rng(3)
        for chunk in range(5):
            members = [b"h%d" % i for i in rng.integers(0, 100_000, 1000)]
            cols = ([], [], [])
            for m in members:
                i, r = hll_ref.pos_val(hll_ref.hash_member(m))
                oracle.insert(m)
                cols[0].append(row); cols[1].append(i); cols[2].append(r)
            table.add_batch(np.array(cols[0], np.int32),
                            np.array(cols[1], np.int32),
                            np.array(cols[2], np.int32))
        table.apply_pending()
        assert table._slot_of[row] >= 0  # promoted mid-interval
        est, regs, _t, _m = table.snapshot_and_reset()
        # pre-promotion backlog folded in: registers exactly match oracle
        np.testing.assert_array_equal(regs[row], oracle.regs)
        assert float(est[row]) == oracle.estimate()

    def test_dev_slot_cap_keeps_overflow_keys_sparse(self):
        """Past MAX_DEV_SLOTS (the HBM guard) hot keys stay on the host
        tier and still estimate correctly."""
        import numpy as np
        from veneur_tpu.ops import hll_ref
        table = self._mk(batch_cap=256, promote_samples=4, max_dev_slots=2)
        rows_of = {}
        for name in (b"cap.a", b"cap.b", b"cap.c", b"cap.d"):
            stub = self._stub(name)
            with table.lock:
                rows_of[name] = table.row_for(stub)
        oracle = {n: hll_ref.HLL() for n in rows_of}
        cols = ([], [], [])
        for n, row in rows_of.items():
            for i in range(200):
                m = b"%s-%d" % (n, i)
                oracle[n].insert(m)
                ix, rh = hll_ref.pos_val(hll_ref.hash_member(m))
                cols[0].append(row); cols[1].append(ix); cols[2].append(rh)
        table.add_batch(np.array(cols[0], np.int32),
                        np.array(cols[1], np.int32),
                        np.array(cols[2], np.int32))
        table.apply_pending()
        assert table._nslots == 2  # capped, not 4
        est, regs, _t, _m = table.snapshot_and_reset()
        for n, row in rows_of.items():
            assert float(est[row]) == oracle[n].estimate(), n
            np.testing.assert_array_equal(regs[row], oracle[n].regs)

    def test_prewarm_dense_promotes_interned_rows(self):
        """prewarm_dense (bench warmup: climb the dev-cap ladder before
        the measured window) promotes every interned row below the slot
        cap; estimates after a real interval stay correct."""
        import numpy as np
        from veneur_tpu.ops import hll_ref
        table = self._mk(batch_cap=256, promote_samples=2048,
                         max_dev_slots=3)
        rows = []
        for name in (b"pw.a", b"pw.b", b"pw.c", b"pw.d"):
            stub = self._stub(name)
            with table.lock:
                rows.append(table.row_for(stub))
        assert table._nslots == 0  # nothing promoted yet (big threshold)
        assert table.prewarm_dense() == 3  # capped at max_dev_slots
        assert sorted(int(table._slot_of[r]) >= 0 for r in rows) == \
            [False, True, True, True]
        # a normal interval after prewarm: samples route per tier and
        # the flush estimates every key correctly
        oracle = {r: hll_ref.HLL() for r in rows}
        cols = ([], [], [])
        for r in rows:
            for i in range(20):
                m = b"%d-%d" % (r, i)
                oracle[r].insert(m)
                ix, rh = hll_ref.pos_val(hll_ref.hash_member(m))
                cols[0].append(r); cols[1].append(ix); cols[2].append(rh)
        table.add_batch(np.array(cols[0], np.int32),
                        np.array(cols[1], np.int32),
                        np.array(cols[2], np.int32))
        table.apply_pending()
        est, regs, _t, _m = table.snapshot_and_reset()
        for r in rows:
            assert float(est[r]) == oracle[r].estimate(), r
            np.testing.assert_array_equal(regs[r], oracle[r].regs)

    def test_import_merge_at_slot_cap_folds_to_host_tier(self):
        """merge_batch past MAX_DEV_SLOTS must fold imported registers
        into the sparse tier, not scatter to slot -1 (which aliases the
        last device row and corrupts another key)."""
        import numpy as np
        from veneur_tpu.ops import hll_ref
        table = self._mk(batch_cap=256, promote_samples=4, max_dev_slots=1)
        # occupy the single device slot with a promoted key
        hot_stub = self._stub(b"imp.hot")
        with table.lock:
            hot_row = table.row_for(hot_stub)
        hot_oracle = hll_ref.HLL()
        cols = ([], [], [])
        for i in range(50):
            m = b"hot-%d" % i
            hot_oracle.insert(m)
            ix, rh = hll_ref.pos_val(hll_ref.hash_member(m))
            cols[0].append(hot_row); cols[1].append(ix); cols[2].append(rh)
        table.add_batch(np.array(cols[0], np.int32),
                        np.array(cols[1], np.int32),
                        np.array(cols[2], np.int32))
        table.apply_pending()
        assert table._slot_of[hot_row] >= 0 and table._nslots == 1
        # import a dense sketch for a DIFFERENT key: promotion is capped
        imp_oracle = hll_ref.HLL()
        for i in range(300):
            imp_oracle.insert(b"imp-%d" % i)
        imp_stub = self._stub(b"imp.capped")
        table.merge_batch([imp_stub], imp_oracle.regs[None, :])
        with table.lock:
            imp_row = table.row_for(imp_stub)
        assert table._slot_of[imp_row] < 0  # stayed on the host tier
        est, regs, _t, _m = table.snapshot_and_reset()
        # the imported key estimates correctly from the host tier...
        assert float(est[imp_row]) == imp_oracle.estimate()
        np.testing.assert_array_equal(regs[imp_row], imp_oracle.regs)
        # ...and the promoted key was not corrupted by a -1 scatter
        assert float(est[hot_row]) == hot_oracle.estimate()
        np.testing.assert_array_equal(regs[hot_row], hot_oracle.regs)

    def test_capacity_clamps_promotion_until_growth(self):
        """With capacity < MAX_DEV_SLOTS the promotion limit is the row
        capacity (slots beyond the table's rows are unreachable); when
        the host table grows, promotion resumes and the device cap grows
        with it."""
        import numpy as np
        from veneur_tpu.core.columnstore import SetTable
        table = SetTable(capacity=8, batch_cap=64, sparse=True,
                         promote_samples=1, max_dev_slots=65536)
        # intern 8 rows at capacity 8
        stubs = [self._stub(b"cl.%d" % i) for i in range(8)]
        with table.lock:
            for s in stubs:
                table.row_for(s)
        assert table.prewarm_dense() == 8
        assert table._nslots == 8
        # at the clamp: a promotion attempt is a no-op, not state growth
        table._promote_locked(0)
        assert table._nslots == 8
        # interning a 9th key doubles the host table; promotion resumes
        extra = self._stub(b"cl.extra")
        with table.lock:
            row9 = table.row_for(extra)
        assert table.capacity == 16
        assert table.prewarm_dense() == 9
        assert table._slot_of[row9] >= 0
        assert table._dev_cap >= 9  # device cap regrew past the old clamp
        # and the dense tier still aggregates for the new slot
        ix, rh = 5, 3
        table.add_batch(np.array([row9], np.int32),
                        np.array([ix], np.int32), np.array([rh], np.int32))
        table.apply_pending()
        est, regs, _t, _m = table.snapshot_and_reset()
        assert regs[row9][ix] == rh

    def test_interval_reset_demotes(self):
        import numpy as np
        table = self._mk(batch_cap=256)
        stub = self._stub(b"sp.reset")
        with table.lock:
            row = table.row_for(stub)
        rows = np.full(4096, row, np.int32)
        idxs = np.arange(4096).astype(np.int32) % 16384
        rhos = np.ones(4096, np.int32)
        table.add_batch(rows, idxs, rhos)
        table.apply_pending()
        assert table._nslots == 1
        table.snapshot_and_reset()
        assert table._nslots == 0  # interval-scoped, like every family
        est, _r, _t, _m = table.snapshot_and_reset()
        assert float(est[row]) == 0.0
