"""HyperLogLog accuracy tests: scalar reference and batched device kernel.
The p=14 sketch has ~0.8% standard error; we allow 3 sigma."""

import numpy as np
import pytest

from veneur_tpu.ops import batch_hll as bhll
from veneur_tpu.ops.hll_ref import HLL, hash_member, pos_val


class TestScalarHLL:
    @pytest.mark.parametrize("n", [100, 1000, 10000, 100000])
    def test_estimate_accuracy(self, n):
        h = HLL()
        for i in range(n):
            h.insert(b"member-%d" % i)
        assert h.estimate() == pytest.approx(n, rel=0.03)

    def test_duplicates_not_counted(self):
        h = HLL()
        for _ in range(5):
            for i in range(1000):
                h.insert(b"m%d" % i)
        assert h.estimate() == pytest.approx(1000, rel=0.03)

    def test_merge(self):
        a, b = HLL(), HLL()
        for i in range(5000):
            a.insert(b"a%d" % i)
            b.insert(b"b%d" % i)
        a.merge(b)
        assert a.estimate() == pytest.approx(10000, rel=0.03)

    def test_merge_overlapping(self):
        a, b = HLL(), HLL()
        for i in range(5000):
            a.insert(b"x%d" % i)
            b.insert(b"x%d" % i)
        a.merge(b)
        assert a.estimate() == pytest.approx(5000, rel=0.03)

    def test_serialization_roundtrip(self):
        a = HLL()
        for i in range(1234):
            a.insert(b"v%d" % i)
        b = HLL.from_bytes(a.to_bytes())
        assert b.estimate() == a.estimate()

    def test_empty(self):
        assert HLL().estimate() == pytest.approx(0, abs=1)


class TestBatchedHLL:
    def _ingest(self, members_by_row, num_keys, batch=4096):
        regs = bhll.init_state(num_keys)
        coo = []
        for row, members in members_by_row.items():
            for member in members:
                idx, rho = pos_val(hash_member(member))
                coo.append((row, idx, rho))
        for i in range(0, len(coo), batch):
            chunk = coo[i:i + batch]
            pad = batch - len(chunk)
            rows = np.array([c[0] for c in chunk] + [num_keys] * pad, np.int32)
            idxs = np.array([c[1] for c in chunk] + [0] * pad, np.int32)
            rhos = np.array([c[2] for c in chunk] + [0] * pad, np.int32)
            regs = bhll.apply_batch(regs, rows, idxs, rhos)
        return regs

    def test_matches_scalar(self):
        members = [b"user-%d" % i for i in range(20000)]
        regs = self._ingest({0: members, 1: members[:500]}, 2)
        scalar = HLL()
        for member in members:
            scalar.insert(member)
        est = bhll.estimate(regs)
        assert float(est[0]) == pytest.approx(scalar.estimate(), rel=1e-6)
        assert float(est[1]) == pytest.approx(500, rel=0.05)
        # registers must be identical to the scalar sketch
        np.testing.assert_array_equal(np.asarray(regs)[0], scalar.regs)

    def test_empty_row_estimates_zero(self):
        regs = bhll.init_state(2)
        est = bhll.estimate(regs)
        assert float(est[0]) == 0.0

    def test_merge_rows(self):
        a = self._ingest({0: [b"a%d" % i for i in range(3000)]}, 2)
        b_scalar = HLL()
        for i in range(3000):
            b_scalar.insert(b"b%d" % i)
        merged = bhll.merge_rows(
            a, np.array([0], np.int32), b_scalar.regs[None, :])
        est = bhll.estimate(merged)
        assert float(est[0]) == pytest.approx(6000, rel=0.03)

    def test_shard_merge(self):
        a = self._ingest({0: [b"m%d" % i for i in range(4000)]}, 1)
        b = self._ingest({0: [b"m%d" % i for i in range(2000, 6000)]}, 1)
        merged = bhll.merge(a, b)
        est = bhll.estimate(merged)
        assert float(est[0]) == pytest.approx(6000, rel=0.03)


class TestScalarKernels:
    def test_counters(self):
        from veneur_tpu.ops import scalars
        state = scalars.init_counters(4)
        rows = np.array([0, 0, 1, 4, 2], np.int32)  # 4 = padding
        vals = np.array([1.0, 2.0, 5.0, 99.0, 1.0], np.float32)
        rates = np.array([1.0, 0.5, 1.0, 1.0, 0.1], np.float32)
        state = scalars.apply_counters(state, rows, vals, rates)
        assert scalars.counter_values(state).tolist() == [5.0, 5.0, 10.0, 0.0]

    def test_counter_truncation_per_sample(self):
        # parity: each sample contributes trunc(value/rate)
        from veneur_tpu.ops import scalars
        state = scalars.init_counters(1)
        rows = np.array([0, 0], np.int32)
        vals = np.array([1.0, 1.0], np.float32)
        rates = np.array([0.3, 0.3], np.float32)
        state = scalars.apply_counters(state, rows, vals, rates)
        # trunc(3.33)*2, not trunc(6.66)
        assert float(scalars.counter_values(state)[0]) == 6.0

    def test_counter_kahan_precision(self):
        # many small batches must not drift past f32 granularity
        from veneur_tpu.ops import scalars
        state = scalars.init_counters(1)
        rows = np.zeros(1024, np.int32)
        vals = np.full(1024, 33.0, np.float32)
        rates = np.ones(1024, np.float32)
        for _ in range(600):  # 600 * 1024 * 33 = 20,275,200 > 2^24
            state = scalars.apply_counters(state, rows, vals, rates)
        got = float(scalars.counter_values(state)[0])
        assert got == 600 * 1024 * 33.0

    def test_gauges_last_write_wins(self):
        from veneur_tpu.ops import scalars
        state = scalars.init_gauges(3)
        rows = np.array([0, 1, 0, 3], np.int32)
        vals = np.array([1.0, 2.0, 7.0, 99.0], np.float32)
        state = scalars.apply_gauges(state, rows, vals)
        assert state["value"].tolist() == [7.0, 2.0, 0.0]
        assert state["set"].tolist() == [True, True, False]
        # second batch: only row 1 updated
        state = scalars.apply_gauges(
            state, np.array([1], np.int32), np.array([5.0], np.float32))
        assert state["value"].tolist() == [7.0, 5.0, 0.0]
