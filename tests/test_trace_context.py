"""Tests for trace context propagation (ambient parenting + HTTP header
inject/extract across the reference's supported formats)."""

from __future__ import annotations

from veneur_tpu import trace
from veneur_tpu.trace import context as tctx


class _Capture:
    def __init__(self):
        self.spans = []

    def send(self, span):
        self.spans.append(span)

    def flush(self):
        pass

    def close(self):
        pass


def make_client():
    backend = _Capture()
    return trace.Client(backend), backend


class TestAmbientParenting:
    def test_nested_spans_share_trace(self):
        client, backend = make_client()
        with tctx.start_span("outer", service="svc", client=client) as outer:
            assert tctx.current_span() is outer
            with tctx.start_span("inner", client=client) as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.proto.parent_id == outer.id
                assert inner.proto.service == "svc"
        assert tctx.current_span() is None
        client.flush()
        client.close()
        assert [s.name for s in backend.spans] == ["inner", "outer"]

    def test_error_flag_on_exception(self):
        client, backend = make_client()
        try:
            with tctx.start_span("boom", service="svc", client=client):
                raise RuntimeError("x")
        except RuntimeError:
            pass
        client.flush()
        client.close()
        assert backend.spans[0].error is True

    def test_global_client(self):
        client, backend = make_client()
        tctx.set_global_client(client)
        try:
            with tctx.start_span("g", service="svc"):
                pass
            client.flush()
            assert [s.name for s in backend.spans] == ["g"]
        finally:
            tctx.set_global_client(None)
            client.close()


class TestHeaderPropagation:
    def test_inject_extract_roundtrip(self):
        client, _ = make_client()
        with tctx.start_span("out", service="svc", client=client) as span:
            headers = tctx.inject_headers(span)
            assert headers["ot-tracer-sampled"] == "true"
            tid, sid = tctx.extract_context(headers)
            assert tid == span.trace_id
            assert sid == span.id
        client.close()

    def test_extract_formats(self):
        cases = [
            ({"ot-tracer-traceid": "ff", "ot-tracer-spanid": "10"},
             (255, 16)),
            ({"Trace-Id": "12", "Span-Id": "34"}, (12, 34)),
            ({"X-Trace-Id": "5", "X-Span-Id": "6"}, (5, 6)),
            ({"Traceid": "7", "Spanid": "8"}, (7, 8)),
            ({}, (0, 0)),
            ({"Trace-Id": "nope", "Span-Id": "1"}, (0, 0)),
        ]
        for headers, want in cases:
            assert tctx.extract_context(headers) == want, headers

    def test_continue_remote_trace(self):
        client, backend = make_client()
        headers = {"Trace-Id": "42", "Span-Id": "7"}
        with tctx.start_span_from_headers("handler", headers,
                                          service="svc", client=client) as s:
            assert s.trace_id == 42
            assert s.proto.parent_id == 7
        client.flush()
        client.close()
        assert backend.spans[0].name == "handler"
