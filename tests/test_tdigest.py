"""Statistical correctness of the scalar t-digest and the batched device
kernel, mirroring the reference's InEpsilon-style tests
(reference tdigest/histo_test.go:16-199)."""

import math
import random

import numpy as np
import pytest

from veneur_tpu.ops import batch_tdigest as btd
from veneur_tpu.ops.tdigest_ref import MergingDigest


def _means_weights(state):
    """Centroid (means, weights) view of a slot-accumulator digest state."""
    w = np.asarray(state["weights"])
    wv = np.asarray(state["wv"])
    means = np.divide(wv, w, out=np.zeros_like(wv), where=w > 0)
    return means, w


def uniform_digest(rng, n=10000):
    td = MergingDigest(100)
    data = [rng.random() for _ in range(n)]
    for x in data:
        td.add(x, 1.0)
    return td, data


class TestScalarDigest:
    def test_uniform_quantiles(self):
        rng = random.Random(42)
        td, data = uniform_digest(rng)
        data.sort()
        for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99):
            got = td.quantile(q)
            want = data[int(q * len(data))]
            assert got == pytest.approx(want, abs=0.02)
        assert td.min == pytest.approx(min(data))
        assert td.max == pytest.approx(max(data))
        assert td.count() == pytest.approx(len(data))
        assert td.sum() == pytest.approx(sum(data), rel=1e-3)

    def test_centroid_count_bounded(self):
        rng = random.Random(7)
        td, _ = uniform_digest(rng, 50000)
        td._merge_all_temps()
        assert len(td.means) <= int(math.pi * 100 / 2 + 0.5)

    def test_cdf(self):
        rng = random.Random(3)
        td, data = uniform_digest(rng)
        for v in (0.1, 0.5, 0.9):
            assert td.cdf(v) == pytest.approx(v, abs=0.02)
        assert td.cdf(-1) == 0.0
        assert td.cdf(2) == 1.0

    def test_merge_two_digests(self):
        rng = random.Random(9)
        a = MergingDigest(100)
        b = MergingDigest(100)
        data = []
        for i in range(20000):
            x = rng.normalvariate(100, 15)
            data.append(x)
            (a if i % 2 == 0 else b).add(x, 1.0)
        a.merge(b, rng=rng)
        data.sort()
        for q in (0.1, 0.5, 0.9):
            want = data[int(q * len(data))]
            assert a.quantile(q) == pytest.approx(want, rel=0.02)
        assert a.count() == pytest.approx(len(data))

    def test_weighted_samples(self):
        td = MergingDigest(100)
        # weight w at value v is equivalent to w repeats
        for v in (1.0, 2.0, 3.0):
            td.add(v, 100.0)
        assert td.count() == pytest.approx(300)
        assert td.quantile(0.5) == pytest.approx(2.0, abs=0.6)

    def test_serialization_roundtrip(self):
        rng = random.Random(5)
        td, _ = uniform_digest(rng)
        td2 = MergingDigest.from_data(td.data())
        for q in (0.1, 0.5, 0.9):
            assert td2.quantile(q) == pytest.approx(td.quantile(q))
        assert td2.count() == pytest.approx(td.count())

    def test_rejects_invalid(self):
        td = MergingDigest(100)
        with pytest.raises(ValueError):
            td.add(math.nan, 1)
        with pytest.raises(ValueError):
            td.add(math.inf, 1)
        with pytest.raises(ValueError):
            td.add(1.0, 0)


class TestBatchedDigest:
    def _ingest(self, per_key_data, num_keys, batch=4096, rng=None):
        """Feed {row: [(value, weight)...]} through apply_batch in chunks."""
        state = btd.init_state(num_keys)
        coo = [(r, v, w) for r, samples in per_key_data.items()
               for (v, w) in samples]
        (rng or random).shuffle(coo)
        for i in range(0, len(coo), batch):
            chunk = coo[i:i + batch]
            pad = batch - len(chunk)
            rows = np.array([c[0] for c in chunk] + [num_keys] * pad, np.int32)
            vals = np.array([c[1] for c in chunk] + [0.0] * pad, np.float32)
            wts = np.array([c[2] for c in chunk] + [0.0] * pad, np.float32)
            state = btd.apply_batch(state, rows, vals, wts)
        # fold staged batches into the main grid, as the table does
        # periodically and at every snapshot
        return btd.compact(state)

    def test_matches_scalar_reference_uniform(self):
        rng = random.Random(11)
        n, num_keys = 20000, 4
        per_key = {k: [(rng.random(), 1.0) for _ in range(n)]
                   for k in range(num_keys)}
        state = self._ingest(per_key, num_keys, rng=rng)
        ps = (0.01, 0.25, 0.5, 0.75, 0.99)
        out = btd.flush_quantiles(state, ps)
        for k in range(num_keys):
            data = sorted(v for v, _ in per_key[k])
            for j, q in enumerate(ps):
                got = float(out["quantiles"][k, j])
                want = data[int(q * len(data))]
                assert got == pytest.approx(want, abs=0.02), (k, q)
            assert float(out["count"][k]) == pytest.approx(n, rel=1e-3)
            assert float(out["sum"][k]) == pytest.approx(sum(data), rel=1e-2)
            assert float(out["min"][k]) == pytest.approx(data[0], abs=1e-6)
            assert float(out["max"][k]) == pytest.approx(data[-1], abs=1e-6)

    def test_lognormal_tail_quantiles(self):
        rng = random.Random(13)
        n = 30000
        data = [rng.lognormvariate(0, 1) for _ in range(n)]
        state = self._ingest({0: [(v, 1.0) for v in data]}, 1, rng=rng)
        out = btd.flush_quantiles(state, (0.5, 0.9, 0.99))
        data.sort()
        for j, q in enumerate((0.5, 0.9, 0.99)):
            got = float(out["quantiles"][0, j])
            want = data[int(q * n)]
            assert got == pytest.approx(want, rel=0.05), q

    def test_weights_respected(self):
        # two values with very different weights shift the median
        state = self._ingest({0: [(0.0, 1.0), (10.0, 9.0)]}, 1)
        out = btd.flush_quantiles(state, (0.5,))
        assert float(out["quantiles"][0, 0]) > 5.0
        assert float(out["count"][0]) == pytest.approx(10.0)

    def test_untouched_rows_unaffected(self):
        rng = random.Random(17)
        state = btd.init_state(3)
        state = self._ingest({0: [(rng.random(), 1.0) for _ in range(1000)]},
                             3, rng=rng)
        before = np.asarray(state["wv"]).copy()
        # a batch touching only row 2 must leave rows 0/1 bit-identical:
        # apply lands in staging, so main rows never move, and rows 0/1
        # gain no staged weight
        rows = np.array([2] * 64, np.int32)
        vals = np.random.default_rng(0).random(64).astype(np.float32)
        wts = np.ones(64, np.float32)
        state = btd.apply_batch(state, rows, vals, wts)
        after = np.asarray(state["wv"])
        np.testing.assert_array_equal(before[0], after[0])
        np.testing.assert_array_equal(before[1], after[1])
        stage_w = np.asarray(state["sweights"])
        assert float(np.sum(stage_w[0])) == 0.0
        assert float(np.sum(stage_w[1])) == 0.0
        assert float(np.sum(stage_w[2])) == 64.0
        # after compaction the staged weight lands in row 2's main grid
        state = btd.compact(state)
        assert float(np.sum(np.asarray(state["weights"])[2])) == 64.0

    def test_centroid_budget(self):
        rng = random.Random(19)
        state = self._ingest(
            {0: [(rng.random(), 1.0) for _ in range(50000)]}, 1, rng=rng)
        nonzero = int(np.sum(np.asarray(state["weights"])[0] > 0))
        assert nonzero <= btd.C

    def test_merge_centroid_rows_import(self):
        # build a digest on host, import it into an empty device table
        rng = random.Random(23)
        td = MergingDigest(100)
        data = [rng.normalvariate(50, 10) for _ in range(20000)]
        for v in data:
            td.add(v)
        td._merge_all_temps()
        m_row, w_row = btd.pack_centroids(td.means, td.weights)
        means = m_row[None, :]
        weights = w_row[None, :]
        state = btd.init_state(2)
        state = btd.merge_centroid_rows(
            state, np.array([0], np.int32), means, weights,
            np.array([td.min], np.float32), np.array([td.max], np.float32),
            np.array([td.reciprocal_sum], np.float32))
        out = btd.flush_quantiles(state, (0.5, 0.9))
        data.sort()
        assert float(out["quantiles"][0, 0]) == pytest.approx(
            data[len(data) // 2], rel=0.02)
        assert float(out["count"][0]) == pytest.approx(len(data), rel=1e-3)
        # row 1 untouched
        assert math.isnan(float(out["quantiles"][1, 0]))

    def test_distributed_merge_equivalence(self):
        """Two shards each ingest half; merging their centroid stores must
        match a single-shard ingest statistically."""
        rng = random.Random(29)
        data = [rng.normalvariate(0, 1) for _ in range(20000)]
        half = len(data) // 2
        s1 = self._ingest({0: [(v, 1.0) for v in data[:half]]}, 1, rng=rng)
        s2 = self._ingest({0: [(v, 1.0) for v in data[half:]]}, 1, rng=rng)
        merged = btd.merge_centroid_rows(
            s1, np.array([0], np.int32),
            *_means_weights(s2),
            np.asarray(s2["dmin"]), np.asarray(s2["dmax"]),
            np.asarray(s2["drecip"]))
        out = btd.flush_quantiles(merged, (0.1, 0.5, 0.9))
        data.sort()
        for j, q in enumerate((0.1, 0.5, 0.9)):
            want = data[int(q * len(data))]
            assert float(out["quantiles"][0, j]) == pytest.approx(
                want, abs=0.05), q
        assert float(out["count"][0]) == pytest.approx(len(data), rel=1e-3)


class TestPackCentroidsMany:
    def test_parity_with_per_key_pack(self):
        """The segmented packer must conserve each digest's mass and
        weighted mean exactly, and may only differ from pack_centroids
        by weight shifting to an ADJACENT k-scale slot (floor(k) flips
        at a bucket boundary from cumsum rounding)."""
        rng = np.random.default_rng(11)
        ms, ws = [], []
        for i in range(800):
            n = int(rng.integers(0, 160))
            m = rng.standard_normal(n) * 100
            w = rng.random(n) * 5
            if n and rng.random() < 0.1:
                w[:] = 0.0                       # weightless digest
            if n and rng.random() < 0.2:
                w[rng.random(n) < 0.4] = 0.0     # holes
            ms.append(m)
            ws.append(w)
        ms.append(np.array([]))                  # empty digest
        ws.append(np.array([]))
        OM, OW = btd.pack_centroids_many(ms, ws)
        exact = 0
        for i in range(len(ms)):
            em, ew = btd.pack_centroids(ms[i], ws[i])
            if (np.allclose(OW[i], ew, atol=1e-6)
                    and np.allclose(OM[i] * OW[i], em * ew, atol=1e-4)):
                exact += 1
                continue
            wmax = ws[i].max() if len(ws[i]) else 0.0
            # an adjacent-slot shift changes exactly one prefix sum by
            # the shifted weight (<= the digest's largest weight)
            np.testing.assert_allclose(
                np.cumsum(OW[i]), np.cumsum(ew), atol=wmax * 1.01 + 1e-9)
            assert abs(OW[i].sum() - ew.sum()) < 1e-9
            assert abs((OM[i] * OW[i]).sum() - (em * ew).sum()) < 1e-4
        # drift must stay rare, not the norm
        assert exact >= len(ms) * 0.97, exact

    def test_empty_batch(self):
        OM, OW = btd.pack_centroids_many([], [])
        assert OM.shape == (0, btd.C) and OW.shape == (0, btd.C)


class TestFusedExportFlush:
    def test_fused_matches_legacy_compact_flush_export(self):
        """flush_export_packed must produce the exact export grid the
        compact->export path produces (same sort, same segment reduce)
        and quantiles within the digest's own tolerance of the legacy
        two-pass flush."""
        import numpy as np

        from veneur_tpu.ops import batch_tdigest as bt

        rng = np.random.default_rng(5)
        K, B = 257, 4096
        ps = (0.25, 0.5, 0.9, 0.99)
        state = bt.init_state(K)
        for _ in range(3):
            rows = rng.integers(0, K, B).astype(np.int32)
            vals = rng.normal(50, 20, B).astype(np.float32)
            wts = rng.choice([1.0, 2.0], B).astype(np.float32)
            state = bt.apply_batch(state, rows, vals, wts)
            state = bt.compact(state)
        rows = rng.integers(0, K, B).astype(np.int32)
        vals = rng.lognormal(1, 1, B).astype(np.float32)
        wts = np.ones(B, np.float32)
        state = bt.apply_batch(state, rows, vals, wts)  # staged, uncompacted

        packed, export_packed = bt.flush_export_packed(state, ps)
        fused_out = bt.unpack_flush(np.asarray(packed), len(ps))
        f_means, f_w, f_min, f_max, f_recip = bt.unpack_export(
            export_packed)

        legacy = bt.compact(dict(state))
        legacy_packed = bt.flush_quantiles_packed(
            legacy, ps, fold_staging=False)
        legacy_out = bt.unpack_flush(np.asarray(legacy_packed), len(ps))
        l_means, l_w, l_min, l_max, l_recip = bt.export_centroids(legacy)

        np.testing.assert_allclose(f_w, l_w, rtol=1e-6, atol=1e-4)
        np.testing.assert_allclose(f_means, l_means, rtol=1e-5, atol=1e-3)
        np.testing.assert_array_equal(f_min, l_min)
        np.testing.assert_array_equal(f_max, l_max)
        np.testing.assert_allclose(f_recip, l_recip, rtol=1e-6)
        np.testing.assert_allclose(fused_out["count"], legacy_out["count"],
                                   rtol=1e-5)
        np.testing.assert_allclose(fused_out["sum"], legacy_out["sum"],
                                   rtol=1e-4)
        # quantiles: fused interpolates over the finer pre-merge grid;
        # both must agree within the digest's own approximation band
        q_f = fused_out["quantiles"]
        q_l = legacy_out["quantiles"]
        spread = np.maximum(legacy_out["max"] - legacy_out["min"], 1e-6)
        rel = np.abs(q_f - q_l) / spread[:, None]
        assert np.nanmax(rel) < 0.05, np.nanmax(rel)
