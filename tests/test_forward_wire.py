"""Native forward-plane serialization: forwardable_to_wire must emit
bytes IDENTICAL to the Python proto path (forwardable_to_protos +
SerializeToString) — the wire format is pinned against Go veneur
interop (reference samplers/metricpb/metric.proto, flusher.go:578-591),
so the native encoder is only acceptable if it is indistinguishable."""

from __future__ import annotations

import numpy as np
import pytest

from veneur_tpu import native
from veneur_tpu.core.columnstore import RowMeta
from veneur_tpu.core.flusher import ForwardableState
from veneur_tpu.forward import convert
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.ops.batch_tdigest import C
from veneur_tpu.samplers.metrics import MetricScope

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def mk_meta(name="t.timer", tags=("a:1", "b:2"), scope=MetricScope.MIXED,
            wire_type="timer"):
    return RowMeta(name=name, tags=list(tags), joined_tags=",".join(tags),
                   digest32=1, scope=scope, wire_type=wire_type)


def mk_histo(meta, means, weights, dmin=0.0, dmax=0.0, drecip=0.0):
    m = np.zeros(C, np.float32)
    w = np.zeros(C, np.float32)
    m[:len(means)] = means
    w[:len(weights)] = weights
    return (meta, m, w, float(dmin), float(dmax), float(drecip))


def wire_of(fwd):
    return [p.SerializeToString() for p in convert.forwardable_to_protos(fwd)]


def native_wire(fwd):
    """forwardable_to_wire, but fail loudly if the histogram rows would
    take the Python-proto fallback — a silent fallback makes every
    byte-parity assertion here vacuously true."""
    if fwd.histograms:
        assert convert._histograms_to_wire(fwd.histograms) is not None, \
            "native digest encoder fell back"
    return convert.forwardable_to_wire(fwd)


class TestByteParity:
    def test_basic_digest(self):
        fwd = ForwardableState(histograms=[
            mk_histo(mk_meta(), [1.5, 2.5, 3.25], [1.0, 4.0, 2.0],
                     dmin=1.5, dmax=3.25, drecip=2.1)])
        assert native_wire(fwd) == wire_of(fwd)

    def test_zero_and_negative_zero_mean(self):
        # proto3 implicit presence is BITWISE in upb: mean=0.0 is
        # omitted from the centroid, mean=-0.0 is emitted
        fwd = ForwardableState(histograms=[
            mk_histo(mk_meta(), [0.0, -0.0, -1.0], [1.0, 2.0, 3.0],
                     dmin=-1.0, dmax=0.0, drecip=0.0)])
        assert native_wire(fwd) == wire_of(fwd)

    def test_empty_digest_row(self):
        fwd = ForwardableState(histograms=[
            mk_histo(mk_meta(), [], [])])
        assert native_wire(fwd) == wire_of(fwd)

    def test_scopes_types_and_tags(self):
        metas = [
            mk_meta("h", ("x:y",), MetricScope.MIXED, "histogram"),
            mk_meta("t", (), MetricScope.GLOBAL_ONLY, "timer"),
            mk_meta("u.with.long.name" * 8, tuple(f"k{i}:v{i}" * 6
                    for i in range(30)), MetricScope.LOCAL_ONLY, "timer"),
        ]
        fwd = ForwardableState(histograms=[
            mk_histo(m, [float(i)], [float(i + 1)]) for i, m in
            enumerate(metas)])
        assert native_wire(fwd) == wire_of(fwd)

    def test_mixed_families_order(self):
        cm = mk_meta("c", wire_type="counter")
        gm = mk_meta("g", wire_type="gauge")
        sm = mk_meta("s", wire_type="set")
        fwd = ForwardableState(
            counters=[(cm, 7.0)], gauges=[(gm, 2.5)],
            histograms=[mk_histo(mk_meta(), [5.0], [3.0], 5, 5, 0.2)],
            sets=[(sm, np.zeros(16384, np.uint8))])
        assert native_wire(fwd) == wire_of(fwd)

    def test_fuzz_random_digests(self):
        rng = np.random.default_rng(7)
        histos = []
        for i in range(64):
            n = int(rng.integers(0, C + 1))
            means = rng.standard_normal(n) * 1e3
            # sprinkle exact zeros / denormals into the mean lanes
            if n:
                means[rng.random(n) < 0.2] = 0.0
            weights = rng.random(n) * 10
            if n:
                weights[rng.random(n) < 0.3] = 0.0  # holes in slot order
            histos.append(mk_histo(
                mk_meta(f"m{i}", (f"t:{i}",)), means, weights,
                dmin=float(rng.standard_normal()),
                dmax=float(rng.standard_normal()),
                drecip=float(rng.random())))
        fwd = ForwardableState(histograms=histos)
        assert native_wire(fwd) == wire_of(fwd)

    def test_wire_parses_back(self):
        fwd = ForwardableState(histograms=[
            mk_histo(mk_meta(), [1.0, 2.0], [3.0, 4.0], 1, 2, 0.5)])
        (blob,) = convert.forwardable_to_wire(fwd)
        pbm = metric_pb2.Metric.FromString(blob)
        assert pbm.name == "t.timer"
        assert pbm.type == metric_pb2.Timer
        cents = pbm.histogram.t_digest.main_centroids
        assert [(c.mean, c.weight) for c in cents] == [(1, 3), (2, 4)]


class TestThroughput:
    def test_50k_keys_under_a_second(self):
        """BASELINE config 4's bar: serializing a 50k-key digest flush
        must be a small fraction of the 10 s interval (the Python proto
        path took ~57 s)."""
        import time
        rng = np.random.default_rng(3)
        histos = []
        for i in range(50_000):
            meta = mk_meta(f"lat.srv.{i & 127}.p", (f"host:h{i & 63}",
                                                    f"az:z{i % 3}"))
            histos.append(mk_histo(
                meta, rng.random(32) * 100, rng.random(32) + 0.01,
                dmin=0.1, dmax=99.0, drecip=1.0))
        fwd = ForwardableState(histograms=histos)
        # cold call pays the per-row frame cache fill (once per key
        # lifetime in production); the steady-state number is the warm one
        convert.forwardable_to_wire(fwd)
        t0 = time.perf_counter()
        wired = convert.forwardable_to_wire(fwd)
        dt = time.perf_counter() - t0
        assert len(wired) == 50_000
        # generous bound for loaded CI machines: the Python proto path
        # this replaced took ~57 s, warm native runs in ~0.15 s
        assert dt < 3.0, f"warm 50k-key serialization took {dt:.2f}s"
