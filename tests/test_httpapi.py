"""Ops HTTP API tests (reference http.go endpoints) and snappy codec
round-trips used by the cortex sink."""

import json
import threading

import pytest

import yaml

import veneur_tpu
from veneur_tpu.core.httpapi import HTTPApi, config_to_dict
from veneur_tpu.util import http as vhttp
from veneur_tpu.util.secret import StringSecret

from test_server import generate_config, setup_server


def api_url(api, path):
    host, port = api.address
    return f"http://{host}:{port}{path}"


class TestHTTPApi:
    def _start(self, cfg=None, **kw):
        api = HTTPApi(cfg or generate_config(), address="127.0.0.1:0", **kw)
        api.start()
        return api

    def test_healthcheck_and_version(self):
        api = self._start()
        try:
            assert vhttp.get(api_url(api, "/healthcheck"))[0] == 200
            assert vhttp.get(api_url(api, "/healthcheck/tracing"))[0] == 200
            status, body = vhttp.get(api_url(api, "/version"))
            assert status == 200
            assert body.decode() == veneur_tpu.__version__
            assert vhttp.get(api_url(api, "/builddate"))[0] == 200
        finally:
            api.stop()

    def test_config_endpoints_redact_secrets(self):
        cfg = generate_config()
        cfg.sentry_dsn = StringSecret("https://supersecret@sentry.invalid/1")
        api = self._start(cfg)
        try:
            _, body = vhttp.get(api_url(api, "/config/json"))
            cfg_json = json.loads(body)
            assert cfg_json["sentry_dsn"] == "REDACTED"
            assert "supersecret" not in body.decode()
            assert cfg_json["interval"] == cfg.interval
            _, body = vhttp.get(api_url(api, "/config/yaml"))
            cfg_yaml = yaml.safe_load(body)
            assert cfg_yaml["sentry_dsn"] == "REDACTED"
        finally:
            api.stop()

    def test_404(self):
        api = self._start()
        try:
            try:
                vhttp.get(api_url(api, "/nope"))
                raise AssertionError("expected HTTPError")
            except vhttp.HTTPError as e:
                assert e.status == 404
        finally:
            api.stop()

    def test_quitquitquit_disabled_by_default(self):
        api = self._start()
        try:
            try:
                vhttp.post(api_url(api, "/quitquitquit"), b"")
                raise AssertionError("expected HTTPError")
            except vhttp.HTTPError as e:
                assert e.status == 404
        finally:
            api.stop()

    def test_server_integration(self):
        server, observer = setup_server(http_address="127.0.0.1:0")
        server.start()
        try:
            status, _ = vhttp.get(api_url(server.http_api, "/healthcheck"))
            assert status == 200
            _, body = vhttp.get(api_url(server.http_api, "/debug/memory"))
            assert isinstance(json.loads(body), list)
        finally:
            server.shutdown()

    def test_config_to_dict_nested(self):
        cfg = generate_config()
        d = config_to_dict(cfg)
        assert d["tpu"]["counter_capacity"] == cfg.tpu.counter_capacity
        assert isinstance(d["percentiles"], list)


class TestSnappy:
    def test_roundtrip_small(self):
        for payload in (b"", b"a", b"hello world" * 3, bytes(range(256))):
            assert vhttp.snappy_decode(vhttp.snappy_encode(payload)) == payload

    def test_roundtrip_large(self):
        payload = b"abcdefgh" * 50_000  # > 64 KiB chunking path
        assert vhttp.snappy_decode(vhttp.snappy_encode(payload)) == payload

    def test_decodes_copies(self):
        # hand-built stream: literal "abcd" + 1-byte-offset copy of 4 back
        stream = bytes([8,            # uvarint length 8
                        3 << 2,       # literal, len 4
                        ]) + b"abcd" + bytes([
                        0b000_001_01 | (0 << 5),  # copy1: len 4+0... build below
                        ])
        # tag for copy-1: type=1, len-4 in bits 2-4, offset high bits 5-7
        tag = 0x01 | ((4 - 4) << 2) | (0 << 5)
        stream = bytes([8, 3 << 2]) + b"abcd" + bytes([tag, 4])
        assert vhttp.snappy_decode(stream) == b"abcdabcd"


class TestProfilingEndpoints:
    def _start(self, cfg=None, **kw):
        api = HTTPApi(cfg or generate_config(), address="127.0.0.1:0", **kw)
        api.start()
        return api

    def test_cpu_profile_request_scoped(self):
        api = self._start()
        try:
            status, body = vhttp.get(
                api_url(api, "/debug/profile/cpu?seconds=0.2"))
            assert status == 200
            assert b"cpu profile:" in body
            assert b"flat%" in body and b"cum%" in body
        finally:
            api.stop()

    def test_cpu_profile_continuous_sampler(self):
        """enable_profiling starts a continuous sampler the endpoint
        reads (reference server.go:1382-1390)."""
        import time

        cfg = generate_config()
        cfg.enable_profiling = True
        server, _observer = setup_server(cfg)
        try:
            server.start()
            assert server.profiler is not None and server.profiler.running
            time.sleep(0.3)  # let the 100 Hz sampler take some samples
            samples, _flat, cum = server.profiler.snapshot()
            assert samples > 0
            assert len(cum) > 0  # other threads' stacks were captured
            report = server.profiler.report()
            assert "cpu profile:" in report
        finally:
            server.shutdown()
        assert not server.profiler.running

    def test_device_trace_endpoint(self):
        """jax.profiler trace zip (TPU analog of /debug/pprof/profile)."""
        import io
        import zipfile

        import jax
        import jax.numpy as jnp

        api = self._start()
        try:
            # give the trace something to record
            import threading

            def burn():
                x = jnp.ones((256, 256))
                for _ in range(5):
                    x = (x @ x).block_until_ready()

            t = threading.Thread(target=burn, daemon=True)
            t.start()
            # the trace itself is 0.3s but the xplane dump on exit
            # scales with accumulated in-process XLA state (~8s deep
            # into the suite): give the request room past vhttp.get's
            # default 10s so the pin is "endpoint works", not "dump is
            # fast under full-suite load"
            status, body = vhttp.get(
                api_url(api, "/debug/profile/device?seconds=0.3"),
                timeout=120.0)
            t.join()
            assert status == 200
            zf = zipfile.ZipFile(io.BytesIO(body))
            assert zf.namelist()  # non-empty trace directory
        finally:
            api.stop()


def _read_varint(buf, pos):
    """Returns (value, new_pos)."""
    v = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


class TestPprofEndpoint:
    @staticmethod
    def _decode(buf):
        """Minimal protobuf reader: yields (tag, wire, value)."""
        pos = 0
        while pos < len(buf):
            key, pos = _read_varint(buf, pos)
            tag, wire = key >> 3, key & 7
            if wire == 2:
                ln, pos = _read_varint(buf, pos)
                yield tag, wire, buf[pos:pos + ln]
                pos += ln
            elif wire == 0:
                v, pos = _read_varint(buf, pos)
                yield tag, wire, v
            else:
                raise AssertionError(f"unexpected wire type {wire}")

    def test_pprof_profile_decodes(self):
        """/debug/pprof/profile returns a structurally valid gzipped
        pprof Profile: sample types, samples referencing locations that
        reference functions, and a string table resolving names."""
        import gzip

        from veneur_tpu.core import profiling

        # busy thread so the sampler sees stacks
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(range(1000))

        t = threading.Thread(target=spin, daemon=True)
        t.start()
        try:
            body = profiling.pprof_for(0.3)
        finally:
            stop.set()
        raw = gzip.decompress(body)
        fields = list(self._decode(raw))
        strings = [v.decode() for tag, _, v in fields if tag == 6]
        assert strings[0] == ""
        assert "samples" in strings and "count" in strings
        assert "cpu" in strings and "nanoseconds" in strings
        samples = [v for tag, _, v in fields if tag == 2]
        locations = [v for tag, _, v in fields if tag == 4]
        functions = [v for tag, _, v in fields if tag == 5]
        assert samples and locations and functions
        # every function's name/filename index resolves in the table
        for fn in functions:
            sub = dict((t2, v2) for t2, _, v2 in self._decode(fn))
            assert 0 < sub[2] < len(strings)  # name
            assert 0 < sub[4] < len(strings)  # filename
        # this test file's spin() must appear in the profile
        assert any("spin" == strings[dict(
            (t2, v2) for t2, _, v2 in self._decode(fn))[2]]
            for fn in functions)
        # sample values: hits and hits*period, packed pairs
        sub = list(self._decode(samples[0]))
        packed_vals = [v for t2, w2, v in sub if t2 == 2][0]
        nums = []
        pos = 0
        while pos < len(packed_vals):
            n, pos = _read_varint(packed_vals, pos)
            nums.append(n)
        assert len(nums) == 2 and nums[1] == nums[0] * 10_000_000

    def test_http_route_serves_pprof(self):
        import gzip
        cfg = generate_config()
        api = HTTPApi(cfg, server=None, address="127.0.0.1:0")
        api.start()
        try:
            status, body = vhttp.get(
                api_url(api, "/debug/pprof/profile?seconds=0.2"),
                timeout=30)
            assert status == 200
            assert gzip.decompress(body)  # valid gzip payload
            status, listing = vhttp.get(api_url(api, "/debug/pprof/"))
            assert status == 200 and b"pprof CPU profile" in listing
        finally:
            api.stop()


class TestHeapPprof:
    def setup_method(self):
        # each test exercises arming fresh: clear the re-arm throttle
        from veneur_tpu.core import profiling
        profiling._heap_last_armed[0] = 0.0

    def teardown_method(self):
        # heap_pprof arms tracemalloc; leaving it on would slow every
        # later test in this process
        import tracemalloc
        if tracemalloc.is_tracing():
            tracemalloc.stop()

    def test_heap_profile_decodes(self):
        import gzip

        from veneur_tpu.core import profiling

        # keep_tracing (the enable_profiling mode) leaves tracemalloc
        # armed; allocate between calls so the second snapshot has
        # content attributable to this file
        profiling.heap_pprof(keep_tracing=True)
        keepalive = [bytearray(4096) for _ in range(200)]
        body = profiling.heap_pprof()
        assert keepalive  # hold the allocations through the snapshot
        raw = gzip.decompress(body)
        fields = list(TestPprofEndpoint._decode(raw))
        strings = [v.decode() for tag, _, v in fields if tag == 6]
        assert "objects" in strings and "space" in strings
        assert "bytes" in strings
        samples = [v for tag, _, v in fields if tag == 2]
        assert samples
        # this test file shows up as an allocation site
        assert any("test_httpapi" in s for s in strings)

    def test_heap_profile_is_request_scoped_by_default(self):
        import tracemalloc

        from veneur_tpu.core import profiling

        assert not tracemalloc.is_tracing()
        profiling.heap_pprof()
        # a single unauthenticated GET must not durably arm 25-frame
        # tracing (it costs real steady-state CPU on the ingest path)
        assert not tracemalloc.is_tracing()

    def test_request_scoped_arming_is_rate_limited(self):
        import pytest as _pytest

        from veneur_tpu.core import profiling

        profiling.heap_pprof()
        # hammering the unauthenticated endpoint must not keep tracing
        # effectively always-on: a second request-scoped arming inside
        # the window is refused (HTTP layer maps it to 429)...
        with _pytest.raises(profiling.HeapProfileThrottled):
            profiling.heap_pprof()
        # ...but the enable_profiling mode (keep_tracing) is exempt
        profiling.heap_pprof(keep_tracing=True)
        # and with tracing already armed there is no re-arm to throttle
        profiling.heap_pprof()

    def test_http_route_serves_heap(self):
        import gzip
        cfg = generate_config()
        api = HTTPApi(cfg, server=None, address="127.0.0.1:0")
        api.start()
        try:
            status, body = vhttp.get(api_url(api, "/debug/pprof/heap"),
                                     timeout=30)
            assert status == 200
            assert gzip.decompress(body)
        finally:
            api.stop()


class TestGoroutinePprof:
    def test_thread_stacks_profile(self):
        import gzip

        from veneur_tpu.core import profiling
        body = profiling.threads_pprof()
        raw = gzip.decompress(body)
        fields = list(TestPprofEndpoint._decode(raw))
        strings = [v.decode() for tag, _, v in fields if tag == 6]
        assert "threads" in strings and "count" in strings
        samples = [v for tag, _, v in fields if tag == 2]
        assert samples  # at least this thread

    def test_http_route(self):
        import gzip
        api = HTTPApi(generate_config(), server=None, address="127.0.0.1:0")
        api.start()
        try:
            status, body = vhttp.get(
                api_url(api, "/debug/pprof/goroutine"), timeout=30)
            assert status == 200 and gzip.decompress(body)
        finally:
            api.stop()


class TestReferencePprofRoutes:
    """Every pprof route the reference mounts (http.go:53-63) responds
    with the right shape."""

    def setup_method(self):
        from veneur_tpu.core import profiling
        profiling._heap_last_armed[0] = 0.0

    def teardown_method(self):
        import tracemalloc
        if tracemalloc.is_tracing():
            tracemalloc.stop()

    def test_all_reference_routes_respond(self):
        import gzip
        api = HTTPApi(generate_config(), server=None, address="127.0.0.1:0")
        api.start()
        try:
            for route in ("/debug/pprof/allocs", "/debug/pprof/block",
                          "/debug/pprof/mutex",
                          "/debug/pprof/threadcreate"):
                status, body = vhttp.get(api_url(api, route), timeout=30)
                assert status == 200, route
                assert gzip.decompress(body), route  # valid pprof gzip
            status, body = vhttp.get(api_url(api, "/debug/pprof/cmdline"))
            assert status == 200 and (b"\x00" in body or b"python" in body)
            status, body = vhttp.get(api_url(api, "/debug/pprof/symbol"))
            assert status == 200 and body.startswith(b"num_symbols:")
            with pytest.raises(vhttp.HTTPError) as ei:
                vhttp.get(api_url(api, "/debug/pprof/trace"))
            assert ei.value.status == 501
        finally:
            api.stop()

    def test_threadcreate_carries_thread_count(self):
        import gzip
        import threading

        from veneur_tpu.core import profiling
        raw = gzip.decompress(profiling.threadcreate_pprof())
        fields = list(TestPprofEndpoint._decode(raw))
        strings = [v.decode() for tag, _, v in fields if tag == 6]
        assert "threadcreate" in strings
        samples = [v for tag, _, v in fields if tag == 2]
        assert samples

    def test_empty_profile_is_valid(self):
        import gzip

        from veneur_tpu.core import profiling
        raw = gzip.decompress(profiling.empty_pprof("contentions"))
        fields = list(TestPprofEndpoint._decode(raw))
        strings = [v.decode() for tag, _, v in fields if tag == 6]
        assert "contentions" in strings
        assert not [v for tag, _, v in fields if tag == 2]  # no samples

    def test_heap_allocs_back_to_back_scrape(self):
        # a scraper walking the index fetches /heap then /allocs inside
        # the arming-throttle window; the second serves the cached
        # capture instead of 429ing (Go serves both freely)
        import gzip

        from veneur_tpu.core import profiling
        api = HTTPApi(generate_config(), server=None, address="127.0.0.1:0")
        api.start()
        try:
            s1, b1 = vhttp.get(api_url(api, "/debug/pprof/heap"),
                               timeout=30)
            s2, b2 = vhttp.get(api_url(api, "/debug/pprof/allocs"),
                               timeout=30)
            assert s1 == 200 and s2 == 200
            assert gzip.decompress(b1) and gzip.decompress(b2)
        finally:
            api.stop()
