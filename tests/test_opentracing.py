"""OpenTracing compatibility layer + buffered/reconnecting trace backends
(reference trace/opentracing.go, trace/backend.go:46-230)."""

import io
import socket
import threading

import pytest

from veneur_tpu import trace as trace_mod
from veneur_tpu.trace import opentracing as ot


class CapturingBackend:
    def __init__(self):
        self.spans = []
        self.flushes = 0

    def send(self, span):
        self.spans.append(span)

    def flush(self):
        self.flushes += 1

    def close(self):
        pass


@pytest.fixture
def setup():
    backend = CapturingBackend()
    client = trace_mod.Client(backend)
    tracer = ot.Tracer(client, service="svc")
    yield tracer, client, backend
    client.close()


class TestTracer:
    def test_root_and_child_lineage(self, setup):
        tracer, client, backend = setup
        root = tracer.start_span("parent", tags={"k": "v"})
        child = tracer.start_span("child", child_of=root)
        child.finish()
        root.finish()
        client.flush()
        assert len(backend.spans) == 2
        c, p = backend.spans
        assert c.trace_id == p.trace_id
        assert c.parent_id == p.id
        assert p.tags["k"] == "v"
        assert p.name == "parent"

    def test_references_follows_from(self, setup):
        tracer, client, backend = setup
        a = tracer.start_span("a")
        b = tracer.start_span(
            "b", references=[ot.follows_from(a.context())])
        assert b.context().trace_id == a.context().trace_id
        a.finish()
        b.finish()

    def test_error_tag_sets_span_error(self, setup):
        tracer, client, backend = setup
        s = tracer.start_span("boom")
        s.set_tag("error", True)
        s.finish()
        client.flush()
        assert backend.spans[0].error

    def test_context_manager_marks_error(self, setup):
        tracer, client, backend = setup
        with pytest.raises(ValueError):
            with tracer.start_span("cm"):
                raise ValueError("x")
        client.flush()
        assert backend.spans[0].error

    def test_baggage_propagates_to_children(self, setup):
        tracer, _, _ = setup
        root = tracer.start_span("r")
        root.set_baggage_item("tenant", "acme")
        child = tracer.start_span("c", child_of=root)
        assert child.get_baggage_item("tenant") == "acme"

    def test_log_kv_becomes_tags(self, setup):
        tracer, client, backend = setup
        s = tracer.start_span("lg")
        s.log_kv({"event": "cache_miss", "n": 3})
        s.finish()
        client.flush()
        assert backend.spans[0].tags["log.event"] == "cache_miss"


class TestInjectExtract:
    def test_http_headers_round_trip(self, setup):
        tracer, _, _ = setup
        span = tracer.start_span("rpc")
        span.set_baggage_item("k", "v")
        carrier = {}
        tracer.inject(span.context(), ot.FORMAT_HTTP_HEADERS, carrier)
        assert "ot-tracer-traceid" in carrier
        back = tracer.extract(ot.FORMAT_HTTP_HEADERS, carrier)
        assert back.trace_id == span.context().trace_id
        assert back.span_id == span.context().span_id
        assert back.baggage == {"k": "v"}

    def test_extract_empty_carrier_raises(self, setup):
        tracer, _, _ = setup
        with pytest.raises(ot.SpanContextCorruptedException):
            tracer.extract(ot.FORMAT_HTTP_HEADERS, {})

    def test_binary_round_trip(self, setup):
        tracer, _, _ = setup
        span = tracer.start_span("bin")
        buf = io.BytesIO()
        tracer.inject(span.context(), ot.FORMAT_BINARY, buf)
        buf.seek(0)
        back = tracer.extract(ot.FORMAT_BINARY, buf)
        assert back.trace_id == span.context().trace_id

    def test_unknown_format_raises(self, setup):
        tracer, _, _ = setup
        with pytest.raises(ot.UnsupportedFormatException):
            tracer.inject(tracer.start_span("x").context(), "jaeger", {})

    def test_server_side_continuation(self, setup):
        tracer, _, _ = setup
        upstream = tracer.start_span("up")
        carrier = {}
        tracer.inject(upstream.context(), ot.FORMAT_HTTP_HEADERS, carrier)
        server_span = ot.start_span_from_headers(tracer, "handle", carrier)
        assert server_span.inner.trace_id == upstream.context().trace_id
        assert server_span.inner.proto.parent_id == \
            upstream.context().span_id


class TestBufferedBackend:
    def test_bursts_on_flush(self):
        inner = CapturingBackend()
        buffered = trace_mod.BufferedBackend(inner, capacity=100)
        client = trace_mod.Client(buffered)
        for i in range(5):
            client.start_span(f"s{i}", service="b").finish()
        client.flush()
        assert len(inner.spans) == 5
        client.close()

    def test_auto_flush_when_full(self):
        inner = CapturingBackend()
        buffered = trace_mod.BufferedBackend(inner, capacity=3)
        for i in range(7):
            buffered.send(object())
        assert len(inner.spans) == 6  # two bursts of 3; 1 still buffered
        buffered.flush()
        assert len(inner.spans) == 7

    def test_failed_sends_counted_not_raised(self):
        class FailingBackend(CapturingBackend):
            def send(self, span):
                raise OSError("down")

        buffered = trace_mod.BufferedBackend(FailingBackend(), capacity=2)
        buffered.send(object())
        buffered.flush()
        assert buffered.dropped == 1


class TestStreamBackendReconnect:
    def test_reconnects_after_server_restart(self):
        """Kill the listener mid-stream; the backend must reconnect with
        backoff and deliver the next span."""
        from veneur_tpu import protocol

        received = []
        accept_sock = socket.socket()
        accept_sock.bind(("127.0.0.1", 0))
        accept_sock.listen(4)
        addr = accept_sock.getsockname()
        stop = threading.Event()

        def serve():
            while not stop.is_set():
                try:
                    conn, _ = accept_sock.accept()
                except OSError:
                    return
                try:
                    span = protocol.read_ssf(conn.makefile("rb"))
                    if span is not None:
                        received.append(span)
                finally:
                    conn.close()  # one span per connection, then drop

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        backend = trace_mod.StreamBackend(addr)
        wait = threading.Event()
        try:
            from veneur_tpu import ssf
            backend.send(ssf.SSFSpan(id=1, trace_id=1, name="a"))
            for _ in range(100):
                if received:
                    break
                wait.wait(0.05)
            assert [s.id for s in received] == [1]
            # the server dropped the connection after span 1. A send into
            # the dead socket can succeed silently (TCP buffering) before
            # the RST surfaces, so keep sending distinct spans until the
            # reconnect path delivers one.
            for i in range(50):
                backend.send(ssf.SSFSpan(id=100 + i, trace_id=1, name="b"))
                wait.wait(0.05)
                if any(s.id >= 100 for s in received):
                    break
            assert any(s.id >= 100 for s in received)
        finally:
            stop.set()
            accept_sock.close()
            backend.close()
