"""Vendor wire-shape pins: the long-tail sinks' request bodies are
validated against schemas transcribed from public vendor API docs
(tests/testdata/vendor_schemas.json) — not against fakes shaped by the
same author as the sink. Byte-fixture analog of the metricpb/SSF/HLL
pins for the JSON vendors."""

from __future__ import annotations

import json
import numbers
import os

import pytest

from tests.test_sinks import CapturingHTTPServer, im, make_span
from veneur_tpu.samplers.metrics import MetricType

HERE = os.path.dirname(os.path.abspath(__file__))
SCHEMAS = json.load(open(os.path.join(HERE, "testdata",
                                      "vendor_schemas.json")))


def check(value, schema, path="$"):
    """Minimal structural validator for the fixture format."""
    if isinstance(schema, str):
        kind = schema
        if kind == "int":
            assert isinstance(value, int) and not isinstance(value, bool), \
                f"{path}: want int, got {value!r}"
        elif kind == "num":
            assert isinstance(value, numbers.Number) \
                and not isinstance(value, bool), \
                f"{path}: want number, got {value!r}"
        elif kind == "str":
            assert isinstance(value, str), f"{path}: want str, got {value!r}"
        elif kind == "object":
            assert isinstance(value, dict), f"{path}: want object"
        elif kind == "map_str_str":
            assert isinstance(value, dict), f"{path}: want object"
            for k, v in value.items():
                assert isinstance(k, str) and isinstance(v, str), \
                    f"{path}.{k}: want str->str, got {v!r}"
        elif kind == "map_str_num":
            assert isinstance(value, dict), f"{path}: want object"
            for k, v in value.items():
                assert isinstance(k, str) and isinstance(v, numbers.Number), \
                    f"{path}.{k}: want str->num, got {v!r}"
        else:
            raise AssertionError(f"unknown schema kind {kind}")
        return
    if "enum" in schema:
        assert value in schema["enum"], \
            f"{path}: {value!r} not in {schema['enum']}"
        return
    stype = schema["type"]
    if stype == "array":
        assert isinstance(value, list), f"{path}: want array"
        assert len(value) >= schema.get("min_items", 0), f"{path}: empty"
        for i, item in enumerate(value):
            check(item, schema["items"], f"{path}[{i}]")
    elif stype == "object":
        assert isinstance(value, dict), f"{path}: want object, got {value!r}"
        for key, sub in schema.get("required", {}).items():
            assert key in value, f"{path}: missing required key {key!r}"
            check(value[key], sub, f"{path}.{key}")
        for key, sub in schema.get("optional", {}).items():
            if key in value:
                check(value[key], sub, f"{path}.{key}")
    else:
        raise AssertionError(f"unknown schema type {stype}")


@pytest.fixture
def fake():
    server = CapturingHTTPServer()
    yield server
    server.close()


class FakeStatsd:
    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass


class FakeServer:
    statsd = FakeStatsd()


def test_datadog_apm_traces_shape(fake):
    from veneur_tpu.sinks.datadog import DatadogSpanSink

    sink = DatadogSpanSink("datadog", trace_api_url=fake.url,
                           hostname="dh")
    sink.start(FakeServer())
    root = make_span(trace_id=9, span_id=9, name="root", service="api",
                     tags={"resource": "GET /x"})
    child = make_span(trace_id=9, span_id=10, parent_id=9, name="child",
                      service="api", error=True)
    sink.ingest(root)
    sink.ingest(child)
    sink.flush()
    assert fake.event.wait(5)
    _path, _headers, body = fake.requests[0]
    payload = json.loads(body)
    check(payload, SCHEMAS["datadog_apm"])
    spans = [s for trace in payload for s in trace]
    by_id = {s["span_id"]: s for s in spans}
    # vendor semantics spot checks: ns timestamps, error code, resource
    assert by_id[9]["parent_id"] == 0
    assert by_id[9]["resource"] == "GET /x"
    assert by_id[10]["error"] != 0
    assert by_id[9]["start"] > 10 ** 17  # nanoseconds, not seconds
    assert by_id[9]["duration"] > 0


def test_newrelic_metrics_shape(fake):
    from veneur_tpu.sinks.newrelic import NewRelicMetricSink

    sink = NewRelicMetricSink(
        "newrelic", insert_key="k", hostname="h1", interval=10.0,
        metric_url=fake.url + "/metric/v1", tags=["env:test"])
    sink.flush([
        im("nr.count", 5, MetricType.COUNTER, tags=("a:b",)),
        im("nr.gauge", 2.5, MetricType.GAUGE),
    ])
    assert fake.event.wait(5)
    body = json.loads(fake.requests[0][2])
    check(body, SCHEMAS["newrelic_metrics"])
    metrics = body[0]["metrics"]
    by_name = {mm["name"]: mm for mm in metrics}
    # counters must be type=count with an interval.ms window
    assert by_name["nr.count"]["type"] == "count"
    assert by_name["nr.count"].get("interval.ms", 0) > 0
    assert by_name["nr.gauge"]["type"] == "gauge"


def test_lightstep_otlp_shape(fake):
    """The lightstep sink speaks OTLP/HTTP JSON (the OpenTelemetry
    ExportTraceServiceRequest shape, which current LightStep/ServiceNow
    collectors accept at /v1/traces) — schema transcribed from the
    public OTLP JSON encoding spec."""
    from veneur_tpu.sinks.lightstep import LightStepSpanSink

    sink = LightStepSpanSink("ls", access_token="tok",
                             collector_url=fake.url)
    sink.ingest(make_span(trace_id=11, span_id=12, name="root",
                          service="svc"))
    sink.ingest(make_span(trace_id=11, span_id=13, parent_id=12,
                          name="child", service="svc", error=True))
    sink.flush()
    assert fake.event.wait(5)
    path, headers, body = fake.requests[0]
    assert path.endswith("/v1/traces")
    lower = {k.lower(): v for k, v in headers.items()}
    assert lower["lightstep-access-token"] == "tok"
    payload = json.loads(body)
    check(payload, SCHEMAS["otlp_traces"])
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    by_id = {s["spanId"]: s for s in spans}
    # OTLP semantics spot checks: fixed-width hex ids, ns-string
    # timestamps, parent link, error status
    root = by_id[format(12, "016x")]
    child = by_id[format(13, "016x")]
    assert len(root["traceId"]) == 32 and root["traceId"].endswith("b")
    assert "parentSpanId" not in root
    assert child["parentSpanId"] == format(12, "016x")
    assert child["status"]["code"] == 2
    assert int(root["startTimeUnixNano"]) > 10 ** 17  # ns, not s
    svc_attr = payload["resourceSpans"][0]["resource"]["attributes"][0]
    assert svc_attr == {"key": "service.name",
                        "value": {"stringValue": "svc"}}


def test_newrelic_trace_shape(fake):
    from veneur_tpu.sinks.newrelic import NewRelicSpanSink

    sink = NewRelicSpanSink(
        "newrelic", insert_key="k", trace_url=fake.url + "/trace/v1",
        common_tags={"env": "test"})
    sink.ingest(make_span(trace_id=7, span_id=8, name="op",
                          service="svc"))
    sink.flush()
    assert fake.event.wait(5)
    body = json.loads(fake.requests[0][2])
    check(body, SCHEMAS["newrelic_trace"])
    span = body[0]["spans"][0]
    assert span["attributes"]["service.name"] == "svc"
    assert span["attributes"]["duration.ms"] == pytest.approx(1000.0)
