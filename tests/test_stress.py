"""Concurrency stress: the race-detector analog for the ingest hot path.

The reference runs its whole suite under `go test -race`
(reference .circleci/config.yml:68-72); Python has no race detector, so
this suite hammers the lock choreography directly: N reader threads, a
concurrent flush ticker, and an import stream all target ONE column
store for a few seconds, then sample conservation is asserted — every
counter increment sent must appear in exactly one flush, and the run
must terminate (no deadlock) within the test timeout.
"""

import os
import threading
import time

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.forward.client import ForwardClient
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.sinks.channel import ChannelMetricSink

# STRESS_DURATION_S=60 turns this into a long-soak hammer (found the
# round-3 lost-sample race at ~1-in-5 four-second runs)
DURATION_S = float(os.environ.get("STRESS_DURATION_S", 4.0))
READERS = 4


def make_server(**overrides):
    cfg = Config()
    cfg.interval = 3600.0  # flushes are driven manually below
    cfg.hostname = "stress"
    cfg.tpu.counter_capacity = 1024
    cfg.tpu.gauge_capacity = 1024
    cfg.tpu.histo_capacity = 1024
    cfg.tpu.set_capacity = 256
    cfg.tpu.batch_cap = 1024
    for k, v in overrides.items():
        setattr(cfg, k, v)
    cfg.apply_defaults()
    observer = ChannelMetricSink()
    return Server(cfg, extra_metric_sinks=[observer]), observer


class TestIngestFlushRaces:
    def test_sample_conservation_under_concurrent_flush(self):
        """Readers + a fast flusher racing on one store: the sum of
        flushed counter values equals exactly the samples ingested —
        nothing lost in a buffer swap, nothing double-counted."""
        server, observer = make_server()
        n_keys = 64
        datagrams = [
            b"\n".join(b"race.c%d:1|c" % k for k in range(n_keys))
            for _ in range(8)]
        sent = [0] * READERS
        stop = threading.Event()

        def reader(slot):
            while not stop.is_set():
                server.handle_packet_batch(datagrams)
                sent[slot] += len(datagrams) * n_keys

        flushed = []

        def flusher():
            while not stop.is_set():
                server.flush()
                for metric in observer.drain():
                    if metric.name.startswith("race.c"):
                        flushed.append(metric.value)
                time.sleep(0.02)

        threads = [threading.Thread(target=reader, args=(i,), daemon=True)
                   for i in range(READERS)]
        threads.append(threading.Thread(target=flusher, daemon=True))
        for t in threads:
            t.start()
        time.sleep(DURATION_S)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "thread failed to stop (deadlock?)"

        # final drain: apply whatever is still pending, flush once more
        server.store.apply_all_pending()
        server.flush()
        for metric in observer.drain():
            if metric.name.startswith("race.c"):
                flushed.append(metric.value)

        assert sum(flushed) == pytest.approx(sum(sent)), (
            f"lost/duplicated samples: flushed {sum(flushed)} "
            f"of {sum(sent)} sent")

    def test_histo_weight_conservation_under_concurrent_flush(self):
        """Timers under racing flushes: total flushed digest weight
        (the .count aggregate) equals samples sent — exercises the
        staging-grid swap + compact + snapshot path."""
        server, observer = make_server(
            aggregates=["count"], percentiles=[0.5])
        rng = np.random.default_rng(0)
        datagrams = [
            b"\n".join(b"race.t%d:%.2f|ms" % (k, v)
                       for k, v in enumerate(rng.normal(50, 5, 32)))
            for _ in range(8)]
        per_batch = 8 * 32
        sent = [0] * READERS
        stop = threading.Event()

        def reader(slot):
            while not stop.is_set():
                server.handle_packet_batch(datagrams)
                sent[slot] += per_batch

        counts = []

        def flusher():
            while not stop.is_set():
                server.flush()
                for metric in observer.drain():
                    if metric.name.endswith(".count"):
                        counts.append(metric.value)
                time.sleep(0.02)

        threads = [threading.Thread(target=reader, args=(i,), daemon=True)
                   for i in range(READERS)]
        threads.append(threading.Thread(target=flusher, daemon=True))
        for t in threads:
            t.start()
        time.sleep(DURATION_S)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "thread failed to stop (deadlock?)"
        server.store.apply_all_pending()
        server.flush()
        for metric in observer.drain():
            if metric.name.endswith(".count"):
                counts.append(metric.value)
        # f32 weight accumulation: exact for these magnitudes
        assert sum(counts) == pytest.approx(sum(sent), rel=1e-6)

    def test_import_stream_races_readers_and_flusher(self):
        """The global-side triple: local readers + forwarded imports +
        flusher on one store; counter conservation across both planes."""
        server, observer = make_server(grpc_address="127.0.0.1:0")
        server.start()
        try:
            client = ForwardClient(server.import_server.address,
                                   deadline=10.0)
            datagrams = [b"\n".join(b"race.m%d:1|c" % k for k in range(32))]
            local_sent = [0] * 2
            import_sent = [0]
            stop = threading.Event()

            def reader(slot):
                while not stop.is_set():
                    server.handle_packet_batch(datagrams)
                    local_sent[slot] += 32

            def importer():
                while not stop.is_set():
                    protos = []
                    for k in range(16):
                        pbm = metric_pb2.Metric()
                        pbm.name = f"race.g{k}"
                        pbm.type = metric_pb2.Counter
                        pbm.scope = metric_pb2.Global
                        pbm.counter.value = 3
                        protos.append(pbm)
                    client.send_protos(protos)
                    import_sent[0] += 16 * 3
                    time.sleep(0.01)

            flushed = []

            def flusher():
                while not stop.is_set():
                    server.flush()
                    for metric in observer.drain():
                        if metric.name.startswith("race."):
                            flushed.append(metric.value)
                    time.sleep(0.02)

            threads = [threading.Thread(target=reader, args=(i,),
                                        daemon=True) for i in range(2)]
            threads.append(threading.Thread(target=importer, daemon=True))
            threads.append(threading.Thread(target=flusher, daemon=True))
            for t in threads:
                t.start()
            time.sleep(DURATION_S)
            stop.set()
            for t in threads:
                t.join(timeout=30)
                assert not t.is_alive(), "thread failed to stop (deadlock?)"
            client.close()
            server.store.apply_all_pending()
            server.flush()
            for metric in observer.drain():
                if metric.name.startswith("race."):
                    flushed.append(metric.value)
            want = sum(local_sent) + import_sent[0]
            assert sum(flushed) == pytest.approx(want)
        finally:
            server.shutdown()

    def test_capacity_growth_under_load(self):
        """Interning new keys (forcing capacity doubles and device-state
        re-layout) while other threads ingest and flush."""
        server, observer = make_server()
        stop = threading.Event()
        sent_known = [0]
        sent_new = [0]

        def known_reader():
            dgram = b"\n".join(b"grow.k%d:1|c" % k for k in range(16))
            while not stop.is_set():
                server.handle_packet_batch([dgram])
                sent_known[0] += 16

        def new_key_reader():
            i = 0
            while not stop.is_set():
                batch = b"\n".join(
                    b"grow.n%d:1|c" % (i + j) for j in range(64))
                server.handle_packet_batch([batch])
                sent_new[0] += 64
                i += 64

        flushed = []

        def flusher():
            while not stop.is_set():
                server.flush()
                for metric in observer.drain():
                    if metric.name.startswith("grow."):
                        flushed.append(metric.value)
                time.sleep(0.05)

        threads = [threading.Thread(target=known_reader, daemon=True),
                   threading.Thread(target=new_key_reader, daemon=True),
                   threading.Thread(target=flusher, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(DURATION_S)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "thread failed to stop (deadlock?)"
        server.store.apply_all_pending()
        server.flush()
        for metric in observer.drain():
            if metric.name.startswith("grow."):
                flushed.append(metric.value)
        assert server.store.counters.capacity > 1024  # growth happened
        assert sum(flushed) == pytest.approx(sent_known[0] + sent_new[0])


class TestSetPromotionRaces:
    def test_every_set_key_emitted_under_concurrent_flush(self):
        """Sets under racing flushes, with keys hot enough to cross the
        sparse->dense promotion threshold mid-interval: every key ever
        sent must appear in at least one flush (a key whose samples
        land in state without a surviving touched flag — or at a stale
        device slot — would vanish instead)."""
        server, observer = make_server()
        stop = threading.Event()
        sent_keys = set()
        lock = threading.Lock()

        def reader(slot):
            gen = 0
            while not stop.is_set():
                names = [b"srace.s%d_%d" % (slot, gen + g) for g in range(4)]
                # enough members per key to cross PROMOTE_SAMPLES after
                # a few batches of re-sends; datagram-sized buffers
                # (oversized buffers are dropped by metric_max_length)
                lines = [b"%s:m%d|s" % (nm, i)
                         for nm in names for i in range(64)]
                batch = [b"\n".join(lines[j:j + 40])
                         for j in range(0, len(lines), 40)]
                for _ in range(3):
                    server.handle_packet_batch(batch)
                with lock:
                    sent_keys.update(n.decode() for n in names)
                gen += 4

        emitted = set()

        def flusher():
            while not stop.is_set():
                server.flush()
                for metric in observer.drain():
                    if metric.name.startswith("srace."):
                        emitted.add(metric.name)
                time.sleep(0.02)

        threads = [threading.Thread(target=reader, args=(i,), daemon=True)
                   for i in range(READERS)]
        threads.append(threading.Thread(target=flusher, daemon=True))
        for t in threads:
            t.start()
        time.sleep(DURATION_S)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "thread failed to stop (deadlock?)"
        server.store.apply_all_pending()
        server.flush()
        for metric in observer.drain():
            if metric.name.startswith("srace."):
                emitted.add(metric.name)
        missing = sent_keys - emitted
        assert not missing, f"{len(missing)} set keys never emitted"


class TestPumpConservation:
    def test_udp_pump_conserves_received_samples_under_flush(self):
        """The C++ pump path: native blaster -> kernel loopback -> pump
        readers -> chunk dispatch, with a concurrent flush hammer.
        Kernel-buffer UDP loss is legal; losing a sample AFTER it was
        counted into store.processed is not — every counted counter
        increment must appear in exactly one flush."""
        import socket

        from veneur_tpu import native

        if not native.available():
            pytest.skip(f"native unavailable: {native.unavailable_reason()}")
        server, observer = make_server(
            statsd_listen_addresses=["udp://127.0.0.1:0"])
        # the server's own flush self-trace spans pass through metric
        # extraction: the 1% span-uniqueness sampling would add
        # ssf.names_unique samples to store.processed that this test's
        # pump.stress.* filter can't see
        server.metric_extraction._uniqueness_rate = 0.0
        server.start()
        flushed_total = [0.0]

        def count_flushes():
            for mm in observer.drain():
                if mm.name.startswith("pump.stress."):
                    flushed_total[0] += mm.value

        try:
            assert server._listeners[0].pump is not None
            # intern the keys so the measured window is all-native
            server.handle_packet_batch(
                [b"\n".join(b"pump.stress.%d:1|c" % i
                            for i in range(64))])
            server.flush()
            observer.drain()
            base = server.store.processed

            datagrams = [
                b"\n".join(b"pump.stress.%d:1|c" % ((j + k) % 64)
                           for k in range(20))
                for j in range(64)]
            blaster = native.Blaster(datagrams)
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.connect(server.local_addr("udp"))
            sent = [0]

            def send():
                # paced: the point is racing flushes, not overload
                sent[0] = blaster.run(sock.fileno(), burst=16,
                                      pace_pps=3000)

            sender = threading.Thread(target=send, daemon=True)
            sender.start()
            t0 = time.time()
            while time.time() - t0 < DURATION_S:
                server.flush()
                count_flushes()
                time.sleep(0.05)
            blaster.stop()
            sender.join(timeout=10)
            # deterministic drain: close the listener (joins the pump
            # readers) and join the dispatcher thread, so no chunk can
            # land between the final flush and the processed read
            listener = server._listeners[0]
            listener.close()
            for t in listener._threads:
                t.join(timeout=30)
                assert not t.is_alive(), "pump drain stuck"
            server.flush()
            count_flushes()
            processed = server.store.processed - base
            assert flushed_total[0] == processed, (
                f"flushed {flushed_total[0]} != processed {processed} "
                f"(sent {sent[0] * 20})")
        finally:
            try:
                sock.close()
            except Exception:
                pass
            server.shutdown()
