"""End-to-end server tests, mirroring the reference integration pattern
(reference server_test.go:66-216): port-0 listeners, short interval, a
channel sink as the universal flush observer, real UDP sockets."""

import socket
import time

import pytest

from veneur_tpu.config import Config, SinkConfig, read_config
from veneur_tpu.core.server import Server
from veneur_tpu.samplers.metrics import MetricType
from veneur_tpu.sinks.channel import ChannelMetricSink


def generate_config(**overrides) -> Config:
    cfg = Config()
    cfg.interval = 0.2
    cfg.num_readers = 1
    cfg.hostname = "test-host"
    cfg.statsd_listen_addresses = []
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 256
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


def setup_server(cfg=None, **overrides):
    cfg = cfg or generate_config(**overrides)
    observer = ChannelMetricSink()
    server = Server(cfg, extra_metric_sinks=[observer])
    return server, observer


def by_name(metrics):
    out = {}
    for metric in metrics:
        out.setdefault(metric.name, []).append(metric)
    return out


class TestLocalFlush:
    def test_counter_gauge_flush(self):
        server, observer = setup_server()
        server.handle_metric_packet(b"a.b.total:5|c")
        server.handle_metric_packet(b"a.b.total:3|c")
        server.handle_metric_packet(b"a.b.level:42.5|g")
        server.flush()
        got = by_name(observer.wait_flush())
        assert got["a.b.total"][0].value == 8.0
        assert got["a.b.total"][0].type == MetricType.COUNTER
        assert got["a.b.level"][0].value == 42.5
        assert got["a.b.level"][0].type == MetricType.GAUGE

    def test_sample_rate_scaling(self):
        server, observer = setup_server()
        server.handle_metric_packet(b"hits:1|c|@0.1")
        server.flush()
        got = by_name(observer.wait_flush())
        assert got["hits"][0].value == 10.0

    def test_state_resets_between_flushes(self):
        server, observer = setup_server()
        server.handle_metric_packet(b"c1:5|c")
        server.flush()
        assert by_name(observer.wait_flush())["c1"][0].value == 5.0
        # second interval: no samples -> sparse (no sink flush at all,
        # matching the reference's early return, flusher.go:92-95)
        server.flush()
        assert observer.queue.empty()
        # third interval: fresh count, not accumulated
        server.handle_metric_packet(b"c1:2|c")
        server.flush()
        assert by_name(observer.wait_flush())["c1"][0].value == 2.0

    def test_mixed_histogram_local_server_emits_aggregates_only(self):
        # a local (forwarding) server emits only aggregates for mixed histos
        server, observer = setup_server(forward_address="fake:1234")
        for v in (1, 2, 3, 4, 5):
            server.handle_metric_packet(b"lat:%d|h" % v)
        server.flush()
        got = by_name(observer.wait_flush())
        assert got["lat.min"][0].value == 1.0
        assert got["lat.max"][0].value == 5.0
        assert got["lat.count"][0].value == 5.0
        assert got["lat.count"][0].type == MetricType.COUNTER
        assert "lat.50percentile" not in got
        assert "lat.median" not in got

    def test_local_only_histogram_gets_percentiles(self):
        server, observer = setup_server(forward_address="fake:1234")
        for v in range(1, 101):
            server.handle_metric_packet(
                b"ll:%d|ms|#veneurlocalonly" % v)
        server.flush()
        got = by_name(observer.wait_flush())
        assert "ll.min" in got and "ll.max" in got and "ll.count" in got
        assert got["ll.50percentile"][0].value == pytest.approx(50, abs=3)
        assert got["ll.99percentile"][0].value == pytest.approx(99, abs=2)

    def test_global_scope_not_emitted_locally(self):
        server, observer = setup_server(forward_address="fake:1234")
        server.handle_metric_packet(b"gc:5|c|#veneurglobalonly")
        server.handle_metric_packet(b"gh:5|h|#veneurglobalonly")
        server.handle_metric_packet(b"users:bob|s")
        server.flush()
        assert observer.queue.empty()

    def test_timer_treated_as_histogram(self):
        server, observer = setup_server()  # global server (no forward)
        for v in (10, 20, 30):
            server.handle_metric_packet(b"t1:%d|ms" % v)
        server.flush()
        got = by_name(observer.wait_flush())
        # global server: percentiles for mixed timers; aggregates emit too
        # because the samples were ingested locally (Local* guards pass,
        # matching flusher.go:360 + samplers.go:359-463)
        assert got["t1.50percentile"][0].value == pytest.approx(20, abs=6)
        assert got["t1.count"][0].value == 3.0
        assert got["t1.min"][0].value == 10.0

    def test_status_check_flush(self):
        server, observer = setup_server()
        server.handle_metric_packet(b"_sc|db.ok|1|#env:x|m:degraded")
        server.flush()
        got = by_name(observer.wait_flush())
        assert got["db.ok"][0].value == 1.0
        assert got["db.ok"][0].type == MetricType.STATUS
        assert got["db.ok"][0].message == "degraded"


class TestGlobalFlush:
    def test_set_estimate_flushed_on_global(self):
        server, observer = setup_server()  # no forward_address -> global
        for i in range(200):
            server.handle_metric_packet(b"uniq:u%d|s" % i)
        server.flush()
        got = by_name(observer.wait_flush())
        assert got["uniq"][0].value == pytest.approx(200, rel=0.05)
        assert got["uniq"][0].type == MetricType.GAUGE

    def test_global_counter_flushed_on_global(self):
        server, observer = setup_server()
        server.handle_metric_packet(b"gc:7|c|#veneurglobalonly")
        server.flush()
        got = by_name(observer.wait_flush())
        assert got["gc"][0].value == 7.0

    def test_global_histogram_digest_aggregates(self):
        server, observer = setup_server()
        for v in range(1, 101):
            server.handle_metric_packet(b"gh:%d|h|#veneurglobalonly" % v)
        server.flush()
        got = by_name(observer.wait_flush())
        # global-scope histo on a global server: digest-derived aggregates
        assert got["gh.min"][0].value == 1.0
        assert got["gh.max"][0].value == 100.0
        assert got["gh.count"][0].value == pytest.approx(100.0)
        assert got["gh.50percentile"][0].value == pytest.approx(50, abs=3)


class TestUDPIngest:
    def test_udp_end_to_end(self):
        cfg = generate_config(
            statsd_listen_addresses=["udp://127.0.0.1:0"])
        server, observer = setup_server(cfg)
        server.start()
        try:
            addr = server.local_addr("udp")
            assert addr is not None
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.sendto(b"udp.test:17|c", addr)
                s.sendto(b"udp.multi:1|c\nudp.multi:2|c", addr)
            deadline = time.time() + 5
            seen = {}
            while time.time() < deadline and len(seen) < 2:
                try:
                    for metric in observer.wait_flush(timeout=1.0):
                        seen[metric.name] = metric
                except Exception:
                    pass
            assert seen["udp.test"].value == 17.0
            assert seen["udp.multi"].value == 3.0
        finally:
            server.shutdown()

    def _unix_roundtrip(self, path: str):
        cfg = generate_config(
            statsd_listen_addresses=[f"unixgram://{path}"])
        server, observer = setup_server(cfg)
        server.start()
        try:
            bind = server.local_addr("unixgram")
            with socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM) as s:
                s.sendto(b"unix.test:5|c", bind)
            deadline = time.time() + 5
            seen = {}
            while time.time() < deadline and "unix.test" not in seen:
                try:
                    for metric in observer.wait_flush(timeout=1.0):
                        seen[metric.name] = metric
                except Exception:
                    pass
            assert seen["unix.test"].value == 5.0
        finally:
            server.shutdown()

    def test_unixgram_end_to_end(self, tmp_path):
        self._unix_roundtrip(str(tmp_path / "statsd.sock"))

    @pytest.mark.skipif(not hasattr(socket, "AF_UNIX")
                        or not __import__("sys").platform.startswith("linux"),
                        reason="abstract sockets are Linux-only")
    def test_abstract_unixgram_end_to_end(self):
        # @name is a Linux abstract socket: no filesystem entry
        # (reference protocol/addr.go handles the @ convention)
        import os
        self._unix_roundtrip(f"@veneur-tpu-test-{os.getpid()}")

    def test_tcp_end_to_end(self):
        cfg = generate_config(
            statsd_listen_addresses=["tcp://127.0.0.1:0"])
        server, observer = setup_server(cfg)
        server.start()
        try:
            addr = server.local_addr("tcp")
            with socket.create_connection(addr) as s:
                s.sendall(b"tcp.test:9|c\n")
            deadline = time.time() + 5
            seen = {}
            while time.time() < deadline and "tcp.test" not in seen:
                try:
                    for metric in observer.wait_flush(timeout=1.0):
                        seen[metric.name] = metric
                except Exception:
                    pass
            assert seen["tcp.test"].value == 9.0
        finally:
            server.shutdown()


class TestSinkRouting:
    def test_routing_and_filters(self):
        from veneur_tpu.config import Features, SinkRoutingConfig
        cfg = generate_config()
        cfg.features.enable_metric_sink_routing = True
        cfg.metric_sink_routing = [SinkRoutingConfig(
            name="r1",
            match=[{"name": {"kind": "prefix", "value": "keep."}}],
            matched=["channel"], not_matched=[])]
        server, observer = setup_server(cfg)
        server.handle_metric_packet(b"keep.me:1|c")
        server.handle_metric_packet(b"drop.me:1|c")
        server.flush()
        got = by_name(observer.wait_flush())
        assert "keep.me" in got
        assert "drop.me" not in got


class TestConfig:
    def test_yaml_and_env(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text(
            "interval: 5s\n"
            "percentiles: [0.5, 0.99]\n"
            "metric_sinks:\n"
            "  - kind: blackhole\n"
            "    name: bh\n"
            "extend_tags: ['env:test']\n")
        cfg = read_config(str(p), env={"VENEUR_INTERVAL": "30s",
                                       "VENEUR_DEBUG": "true"})
        assert cfg.interval == 30.0
        assert cfg.debug is True
        assert cfg.percentiles == [0.5, 0.99]
        assert cfg.metric_sinks[0].kind == "blackhole"
        assert cfg.is_local is False

    def test_defaults(self):
        cfg = Config().apply_defaults()
        assert cfg.interval == 10.0
        assert cfg.metric_max_length == 4096
        assert cfg.aggregates == ["min", "max", "count"]

    def test_duration_parsing(self):
        from veneur_tpu.config import parse_duration
        assert parse_duration("10s") == 10.0
        assert parse_duration("500ms") == 0.5
        assert parse_duration("1m30s") == 90.0
        assert parse_duration(3) == 3.0
        with pytest.raises(ValueError):
            parse_duration("10 parsecs")


class TestNestedEnvOverlay:
    def test_tpu_fields_from_env(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("interval: 5s\n")
        cfg = read_config(str(p), env={
            "VENEUR_TPU_HISTO_CAPACITY": "12345",
            "VENEUR_TPU_DISABLE_NATIVE_PARSER": "true",
            "VENEUR_INTERVAL": "20s",
        })
        assert cfg.tpu.histo_capacity == 12345
        assert cfg.tpu.disable_native_parser is True
        assert cfg.interval == 20.0

    def test_empty_tpu_section_tolerated(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("interval: 5s\ntpu:\n")  # empty section -> None
        cfg = read_config(str(p), env={"VENEUR_TPU_SET_CAPACITY": "777"})
        assert cfg.tpu.set_capacity == 777
