"""Cross-tier self-tracing & exemplars (trace/store.py, the gRPC
metadata carrier in forward/wire.py, and the propagation seams in the
forward client, proxy, and import server): carrier round-trips incl.
the V1->V2 fallback, hedged-duplicate dedupe yielding ONE span tree,
the local->proxy->global acceptance topology, exemplar latest-wins
merges + OpenMetrics rendering, and the slow-marked overhead soak."""

import json
import time
import urllib.request

import grpc
import pytest

from veneur_tpu import trace as trace_mod
from veneur_tpu.config import Config
from veneur_tpu.core.server import Server
from veneur_tpu.forward import wire
from veneur_tpu.forward.protos import metric_pb2
from veneur_tpu.sinks.channel import ChannelMetricSink
from veneur_tpu.testing.forwardtest import ForwardTestServer
from veneur_tpu.trace import context as trace_ctx
from veneur_tpu.trace import opentracing as ot
from veneur_tpu.trace.store import (
    ExemplarStore, SelfTracePlane, TraceStore, decode_exemplars,
    encode_exemplars, parse_trace_id, trace_id_hex)

pytestmark = pytest.mark.tracing


def make_config(**overrides) -> Config:
    cfg = Config()
    cfg.interval = 10.0
    cfg.hostname = "test"
    cfg.tpu.counter_capacity = 128
    cfg.tpu.gauge_capacity = 128
    cfg.tpu.histo_capacity = 128
    cfg.tpu.set_capacity = 64
    cfg.tpu.batch_cap = 512
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg.apply_defaults()


def wait_until(fn, timeout=10.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(step)
    return False


class _FakeCtx:
    """Duck-typed grpc.ServicerContext carrying invocation metadata."""

    def __init__(self, md):
        self._md = tuple(md or ())

    def invocation_metadata(self):
        return self._md


# -- carriers --------------------------------------------------------------

class TestGrpcMetadataCarrier:
    def test_inject_extract_list_carrier(self):
        tracer = ot.Tracer(service="svc")
        span = tracer.start_span("op")
        carrier = []
        tracer.inject(span.context(), ot.FORMAT_GRPC_METADATA, carrier)
        assert carrier and carrier[0][0] == wire.TRACE_KEY
        got = tracer.extract(ot.FORMAT_GRPC_METADATA, carrier)
        assert got.trace_id == span.context().trace_id
        assert got.span_id == span.context().span_id

    def test_inject_extract_dict_carrier(self):
        tracer = ot.Tracer(service="svc")
        ctx = ot.SpanContext(trace_id=0x1234, span_id=0x99)
        carrier = {}
        tracer.inject(ctx, ot.FORMAT_GRPC_METADATA, carrier)
        got = tracer.extract(ot.FORMAT_GRPC_METADATA, carrier)
        assert (got.trace_id, got.span_id) == (0x1234, 0x99)

    def test_extract_from_servicer_context(self):
        md = wire.trace_metadata(77, 88)
        got = ot.Tracer().extract(ot.FORMAT_GRPC_METADATA, _FakeCtx(md))
        assert (got.trace_id, got.span_id) == (77, 88)

    def test_extract_empty_carrier_raises(self):
        with pytest.raises(ot.SpanContextCorruptedException):
            ot.Tracer().extract(ot.FORMAT_GRPC_METADATA, [])

    def test_http_header_parity(self):
        """The same context injected via the HTTP-header carrier and the
        gRPC-metadata carrier extracts to identical lineage."""
        tracer = ot.Tracer(service="svc")
        ctx = ot.SpanContext(trace_id=314159, span_id=271828)
        headers, metadata = {}, []
        tracer.inject(ctx, ot.FORMAT_HTTP_HEADERS, headers)
        tracer.inject(ctx, ot.FORMAT_GRPC_METADATA, metadata)
        via_http = tracer.extract(ot.FORMAT_HTTP_HEADERS, headers)
        via_grpc = tracer.extract(ot.FORMAT_GRPC_METADATA, metadata)
        assert (via_http.trace_id, via_http.span_id) == \
               (via_grpc.trace_id, via_grpc.span_id) == (314159, 271828)


class TestWireHelpers:
    def test_trace_metadata_roundtrip(self):
        md = wire.trace_metadata(123, 456)
        assert wire.extract_trace(_FakeCtx(md)) == (123, 456)

    def test_untraced_is_none(self):
        assert wire.trace_metadata(0, 5) is None
        assert wire.extract_trace(_FakeCtx(())) == (0, 0)

    def test_junk_value_degrades(self):
        assert wire.parse_trace_value("nonsense") == (0, 0)
        assert wire.parse_trace_value("a:b") == (0, 0)

    def test_combine_metadata(self):
        a = wire.token_metadata("t1")
        b = wire.trace_metadata(1, 2)
        combined = wire.combine_metadata(a, None, b)
        assert len(combined) == 2
        assert wire.combine_metadata(None, None) is None

    def test_trace_id_hex_roundtrip(self):
        assert parse_trace_id(trace_id_hex(0xdeadbeef)) == 0xdeadbeef
        assert parse_trace_id("") == 0
        assert parse_trace_id("zz") == 0


# -- trace store -----------------------------------------------------------

class TestTraceStore:
    def test_record_and_report(self):
        store = TraceStore()
        store.record(7, 1, 0, "flush", "svc", 10, 20,
                     tags={"interval": "3"})
        store.record(7, 2, 1, "flush.sink", "svc", 11, 19)
        rep = store.report()
        assert len(rep["traces"]) == 1
        trace = rep["traces"][0]
        assert trace["trace_id"] == trace_id_hex(7)
        assert trace["interval"] == 3
        assert trace["span_count"] == 2
        assert trace["roots"] == [1]

    def test_filters(self):
        store = TraceStore()
        store.record(1, 1, 0, "a", "s", 0, 1, tags={"interval": "1"})
        store.record(2, 2, 0, "b", "s", 0, 1, tags={"interval": "2"})
        assert len(store.report(trace_id=trace_id_hex(2))["traces"]) == 1
        assert store.report(interval=1)["traces"][0]["spans"][0]["name"] \
            == "a"
        assert len(store.report(limit=1)["traces"]) == 1

    def test_bounds(self):
        store = TraceStore(max_traces=2, max_spans=2)
        for tid in (1, 2, 3):
            store.record(tid, tid * 10, 0, "x", "s", 0, 1)
        assert len(store) == 2
        assert store.traces_evicted == 1
        store.record(3, 31, 30, "y", "s", 0, 1)
        store.record(3, 32, 30, "z", "s", 0, 1)  # over the span cap
        assert store.spans_dropped == 1


class TestExemplarStore:
    def test_latest_wins_merge(self):
        ex = ExemplarStore()
        ex.merge("m", 1, 5.0, ts=100.0)
        ex.merge("m", 2, 6.0, ts=50.0)   # older: ignored
        assert ex.get("m")[0] == 1
        ex.merge("m", 3, 7.0, ts=200.0)  # newer: wins
        assert ex.get("m") == (3, 7.0, 200.0)

    def test_for_series_suffix_and_bucket_bounds(self):
        ex = ExemplarStore()
        ex.capture("lat", 3.0, trace_id=9, ts=1.0)
        assert ex.for_series("lat") == (9, 3.0, 1.0)
        assert ex.for_series("lat.sum") == (9, 3.0, 1.0)
        # a bucket line only carries the exemplar when its bound
        # contains the value
        assert ex.for_series("lat.bucket", ["le:2.9"]) is None
        assert ex.for_series("lat.bucket", ["le:3.1"]) == (9, 3.0, 1.0)
        assert ex.for_series("lat.bucket", ["le:+Inf"]) == (9, 3.0, 1.0)
        assert ex.for_series("other") is None

    def test_wire_roundtrip_and_junk(self):
        entries = [("a.b", 0xabc, 1.5, 100.25), ("c", 7, 2.0, 99.0)]
        data = encode_exemplars(entries)
        assert decode_exemplars(data) == entries
        assert decode_exemplars(b"not json") == []
        assert decode_exemplars(b"[[1,2]]") == []
        # hostile deep nesting (RecursionError inside json) must
        # degrade to "no exemplars", never escape into the import
        # handler's token bookkeeping
        assert decode_exemplars(b"[" * 10000 + b"]" * 10000) == []
        assert encode_exemplars([]) is None

    def test_bounded_names(self):
        ex = ExemplarStore(max_names=2)
        for i in range(4):
            ex.capture(f"n{i}", float(i), trace_id=1)
        assert len(ex) == 2


class TestPlane:
    def test_sampling_gate(self):
        plane = SelfTracePlane(sample_rate=0.0)
        assert not plane.interval_sampled
        assert plane.active_trace_hex() == ""
        plane.maybe_capture("x", 1.0, always=True)
        assert len(plane.exemplars) == 0

    def test_follow_gates_recording_only(self):
        plane = SelfTracePlane(sample_rate=0.0)
        assert plane.follow(12345) is False
        assert plane.span("s", 12345) is None
        on = SelfTracePlane(sample_rate=1.0)
        assert on.follow(12345) is True
        span = on.span("s", 12345, parent_id=7)
        span.finish()
        rep = on.store.report(trace_id=trace_id_hex(12345))
        assert rep["traces"][0]["spans"][0]["parent_id"] == 7

    def test_watch_and_budget(self):
        plane = SelfTracePlane()
        plane.set_watch(["hot"])
        plane.maybe_capture("cold", 1.0)
        plane.maybe_capture("hot", 2.0)
        plane.maybe_capture("hot", 3.0)  # first-per-interval wins
        entry = plane.exemplars.get("hot")
        assert entry is not None and entry[1] == 2.0
        assert plane.exemplars.get("cold") is None
        plane.roll()
        plane.maybe_capture("hot", 4.0)
        assert plane.exemplars.get("hot")[1] == 4.0


# -- transport paths -------------------------------------------------------

def _mk_meta(name):
    from veneur_tpu.core.columnstore import RowMeta
    from veneur_tpu.samplers.metrics import MetricScope
    return RowMeta(name=name, tags=[], joined_tags="", digest32=1,
                   scope=MetricScope.GLOBAL_ONLY, wire_type="counter")


def _ambient(span):
    return trace_ctx._current_span.set(span)


class TestForwardClientCarries:
    def test_v1_fallback_keeps_trace_metadata(self):
        """A V2-only importer refuses the bulk body; the V2 retry of the
        SAME flush still carries the trace + exemplar sidecars."""
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.client import ForwardClient

        received = []
        ft = ForwardTestServer(received.extend)  # V2-only
        ft.start()
        plane = SelfTracePlane()
        plane.exemplars.capture("hh", 4.5, trace_id=555, ts=12.0)
        try:
            client = ForwardClient(ft.address, deadline=10.0,
                                   trace_plane=plane)
            fwd = ForwardableState()
            fwd.counters.append((_mk_meta("fb.count"), 4.0))
            parent = trace_mod.Span(None, "flush", "t", trace_id=555)
            token = _ambient(parent)
            try:
                assert client.forward(fwd) == 1
            finally:
                trace_ctx._current_span.reset(token)
            assert client._v1_ok is False  # pinned: the V2 path ran
            assert wait_until(lambda: len(ft.call_metadata) >= 1)
            md = ft.call_metadata[-1]
            assert md[wire.TRACE_KEY] == f"555:{parent.id}"
            blob = md["x-veneur-exemplars-bin"]
            assert decode_exemplars(blob) == [("hh", 555, 4.5, 12.0)]
            client.close()
        finally:
            ft.stop()

    def test_unsampled_interval_sends_no_trace_metadata(self):
        from veneur_tpu.core.flusher import ForwardableState
        from veneur_tpu.forward.client import ForwardClient

        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        try:
            client = ForwardClient(ft.address, deadline=10.0,
                                   trace_plane=SelfTracePlane())
            fwd = ForwardableState()
            fwd.counters.append((_mk_meta("plain.count"), 1.0))
            assert client.forward(fwd) == 1  # no ambient span set
            assert wait_until(lambda: len(ft.call_metadata) >= 1)
            assert wire.TRACE_KEY not in ft.call_metadata[-1]
            client.close()
        finally:
            ft.stop()


class TestSinkExemplarRules:
    def test_counter_only_one_line_per_family(self):
        from veneur_tpu.samplers.metrics import InterMetric, MetricType
        from veneur_tpu.sinks.prometheus import render_exposition
        ex = ExemplarStore()
        ex.capture("lat", 3.0, trace_id=5, ts=1.0)
        ex.capture("hits", 7.0, trace_id=5, ts=1.0)

        def source(name, tags):
            from veneur_tpu.trace.store import (
                render_openmetrics_exemplar)
            entry = ex.for_series(name, tags)
            return (render_openmetrics_exemplar(entry)
                    if entry else None)

        metrics = [
            InterMetric(name="lat.50percentile", timestamp=1, value=2.0,
                        tags=[], type=MetricType.GAUGE),
            InterMetric(name="lat.sum", timestamp=1, value=9.0,
                        tags=[], type=MetricType.GAUGE),
            InterMetric(name="lat.count", timestamp=1, value=3.0,
                        tags=[], type=MetricType.COUNTER),
            InterMetric(name="lat.bucket", timestamp=1, value=1.0,
                        tags=["le:2.0"], type=MetricType.COUNTER),
            InterMetric(name="lat.bucket", timestamp=1, value=3.0,
                        tags=["le:3.1"], type=MetricType.COUNTER),
            InterMetric(name="lat.bucket", timestamp=1, value=3.0,
                        tags=["le:+Inf"], type=MetricType.COUNTER),
            InterMetric(name="hits", timestamp=1, value=7.0,
                        tags=[], type=MetricType.COUNTER),
        ]
        text = render_exposition(metrics, exemplars=source)
        ex_lines = [ln for ln in text.splitlines() if "trace_id=" in ln]
        # the llhist family: ONLY the tightest containing bucket —
        # never the gauges (.sum, percentiles) and not .count; the
        # heavy-hitter counter takes its own exact-name exemplar
        assert sorted(ln.split("{")[0].split(" ")[0]
                      for ln in ex_lines) == ["hits", "lat_bucket"]
        assert 'le="3.1"' in next(ln for ln in ex_lines
                                  if ln.startswith("lat_bucket"))

    def test_parse_tolerates_clause_and_keeps_hash_labels(self):
        from veneur_tpu.sources.openmetrics import parse_exposition
        text = ('foo{msg="err # {code} 5"} 1\n'
                'bar_bucket{le="2.0"} 3 # {trace_id="abc"} 1.5 99.0\n')
        got = {name: (labels, value)
               for _t, name, labels, value in parse_exposition(text)}
        # a quoted label value containing " # {...}" still parses
        assert got["foo"] == ({"msg": "err # {code} 5"}, 1.0)
        # and the exemplified line isn't silently dropped
        assert got["bar_bucket"] == ({"le": "2.0"}, 3.0)


class TestHostileExemplarBlobNoTokenWedge:
    def test_import_retry_passes_after_hostile_blob(self):
        """A hostile exemplar sidecar must not wedge the idempotency
        token in-flight: the send still merges, and a RETRY with the
        same token is answered as a duplicate (not refused forever)."""
        from veneur_tpu.forward.server import ImportServer
        from veneur_tpu.trace.store import EXEMPLAR_KEY

        gserver = Server(make_config(),
                         extra_metric_sinks=[ChannelMetricSink()])
        imp = ImportServer(gserver, "127.0.0.1:0")
        imp.start()
        try:
            pbm = metric_pb2.Metric(name="hostile.c",
                                    type=metric_pb2.Counter,
                                    scope=metric_pb2.Global)
            pbm.counter.value = 1
            body = wire._frame_v1(pbm)
            md = wire.combine_metadata(
                wire.token_metadata("hostile-tok"),
                wire.trace_metadata(111, 222),
                ((EXEMPLAR_KEY, b"[" * 2000 + b"]" * 2000),))
            ch = grpc.insecure_channel(imp.address)
            send_v1 = ch.unary_unary(
                "/forwardrpc.Forward/SendMetrics",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            r1 = send_v1(body, metadata=md)
            assert wire.decode_flow_counts(r1)["merged"] == 1
            r2 = send_v1(body, metadata=md)
            assert wire.decode_flow_counts(r2)["duplicate"] is True
            ch.close()
        finally:
            imp.stop()
            gserver.shutdown()


class TestHedgedDuplicateOneTree:
    def test_token_dedupe_discards_loser_span(self):
        """Two attempts with the SAME idempotency token + trace lineage
        (a hedge pair, or a retry of a landed send) must yield exactly
        one import.merge span — the loser is dropped whole before any
        tracing work happens."""
        from veneur_tpu.forward.server import ImportServer

        gserver = Server(make_config(),
                         extra_metric_sinks=[ChannelMetricSink()])
        imp = ImportServer(gserver, "127.0.0.1:0")
        imp.start()
        try:
            pbm = metric_pb2.Metric(name="hedge.c",
                                    type=metric_pb2.Counter,
                                    scope=metric_pb2.Global)
            pbm.counter.value = 3
            body = wire._frame_v1(pbm)
            md = wire.combine_metadata(
                wire.token_metadata("hedge-tok-1"),
                wire.trace_metadata(909, 808))
            ch = grpc.insecure_channel(imp.address)
            send_v1 = ch.unary_unary(
                "/forwardrpc.Forward/SendMetrics",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b)
            r1 = send_v1(body, metadata=md)
            r2 = send_v1(body, metadata=md)  # the hedged duplicate
            assert wire.decode_flow_counts(r1)["merged"] == 1
            assert wire.decode_flow_counts(r2)["duplicate"] is True
            assert imp.duplicates_dropped_total == 1
            rep = gserver.trace_plane.store.report(
                trace_id=trace_id_hex(909))
            spans = rep["traces"][0]["spans"]
            merges = [s for s in spans if s["name"] == "import.merge"]
            assert len(merges) == 1  # ONE connected tree, loser gone
            assert merges[0]["parent_id"] == 808
            ch.close()
        finally:
            imp.stop()
            gserver.shutdown()


# -- the acceptance topology ----------------------------------------------

def _http_json(api, path):
    host, port = api.address
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as resp:
        return json.loads(resp.read())


class TestForwardtestTopology:
    def test_one_connected_trace_and_exemplar(self):
        """ISSUE 10 acceptance: a local->proxy->global run yields, for
        one flush interval, a single connected trace (shared trace_id,
        resolvable parent links) spanning the local flush root,
        proxy.route, global import.merge, and global sink-ack spans —
        retrievable from /debug/traces on all three tiers — and an
        OpenMetrics exposition line for a llhist series carrying an
        exemplar whose trace_id matches after the forward merge."""
        from veneur_tpu.core.httpapi import HTTPApi
        from veneur_tpu.proxy.proxy import create_static_proxy
        from veneur_tpu.sinks.prometheus import PrometheusMetricSink

        prom = PrometheusMetricSink("prometheus")
        gobs = ChannelMetricSink()
        gserver = Server(
            make_config(grpc_address="127.0.0.1:0",
                        http_address="127.0.0.1:0"),
            extra_metric_sinks=[gobs, prom])
        gserver.start()
        proxy = create_static_proxy([gserver.import_server.address])
        proxy.start()
        proxy_api = HTTPApi({}, server=None, address="127.0.0.1:0",
                            telemetry=proxy.telemetry,
                            traces=proxy.trace_plane.report)
        proxy_api.start()
        local = Server(
            make_config(forward_address=proxy.address,
                        http_address="127.0.0.1:0"),
            extra_metric_sinks=[ChannelMetricSink()])
        local.start()
        try:
            local.handle_metric_packet(b"topo.gc:5|c|#veneurglobalonly")
            local.handle_metric_packet(b"topo.lat:3|l")
            local.flush()
            local.trace_client.flush()
            assert wait_until(
                lambda: gserver.import_server.imported_total >= 1)
            gserver.flush()
            gserver.trace_client.flush()
            gobs.wait_flush(timeout=10)

            lrep = _http_json(local.http_api, "/debug/traces")
            tid = lrep["traces"][-1]["trace_id"]
            assert tid
            prep = _http_json(proxy_api, f"/debug/traces?trace_id={tid}")
            grep_ = _http_json(gserver.http_api,
                               f"/debug/traces?trace_id={tid}")
            assert prep["traces"] and grep_["traces"]

            spans = []
            for rep in (lrep, prep, grep_):
                for trace in rep["traces"]:
                    if trace["trace_id"] == tid:
                        spans.extend(trace["spans"])
            names = {s["name"] for s in spans}
            assert {"flush", "flush.sink", "proxy.route",
                    "proxy.dest.send", "import.merge"} <= names
            # exactly one root across ALL tiers: the local flush span;
            # every other span's parent link resolves
            by_id = {s["span_id"]: s for s in spans}
            roots = [s for s in spans
                     if not s["parent_id"] or s["parent_id"] not in by_id]
            assert len(roots) == 1 and roots[0]["name"] == "flush"
            # two flush spans total (local root + global child), two
            # tiers' worth of sink-ack spans in the same tree
            assert sum(1 for s in spans if s["name"] == "flush") == 2

            # exemplar: the llhist series' OpenMetrics exposition on
            # the GLOBAL carries the interval's trace id after the
            # forward merge; the plain 0.0.4 rendering stays clean
            # (mid-line `#` would break 0.0.4 parsers)
            exposition = prom.exposition_openmetrics()
            assert f'# {{trace_id="{tid}"}} 3' in exposition
            assert exposition.endswith("# EOF\n")
            assert "trace_id=" not in prom.exposition_plain()
            ex_sink_lines = [ln for ln in exposition.splitlines()
                             if "trace_id=" in ln]
            # exactly the bucket line — never gauges (percentiles,
            # .sum) and at most one line per exemplar family
            assert ex_sink_lines == [ln for ln in ex_sink_lines
                                     if ln.startswith("topo_lat_bucket")]
            assert len(ex_sink_lines) == 1  # tightest containing bucket
            # the repo's own exposition parser survives the clause
            from veneur_tpu.sources.openmetrics import parse_exposition
            parsed_names = {n for _t, n, _l, _v
                            in parse_exposition(exposition)}
            # the exemplified bucket line parses instead of being
            # silently dropped
            assert "topo_lat_bucket" in parsed_names
            # /metrics on the global renders plane counters; exemplars
            # only under OpenMetrics content negotiation
            host, port = gserver.http_api.address
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10) as resp:
                metrics_text = resp.read().decode()
            assert "veneur_trace_store_spans_recorded_total" in \
                metrics_text
            assert "veneur_exemplar_merged_total" in metrics_text
            assert "trace_id=" not in metrics_text  # plain scrape
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/metrics",
                headers={"Accept": "application/openmetrics-text"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                om_type = resp.headers.get("Content-Type", "")
                om_text = resp.read().decode()
            assert "openmetrics-text" in om_type
            assert om_text.endswith("# EOF\n")
            ex_lines = [ln for ln in om_text.splitlines()
                        if "# {trace_id=" in ln]
            # counters only (exemplars on gauges are invalid
            # OpenMetrics), at most once per metric name
            assert ex_lines
            assert all("_total" in ln.split(" ")[0] or "_count" in
                       ln.split(" ")[0] for ln in ex_lines)
        finally:
            local.shutdown()
            proxy_api.stop()
            proxy.stop()
            gserver.shutdown()


class TestEventAndLedgerCrossLinks:
    def test_events_and_ledger_carry_interval_trace(self):
        from veneur_tpu.core.httpapi import HTTPApi

        server = Server(make_config(http_address="127.0.0.1:0"),
                        extra_metric_sinks=[ChannelMetricSink()])
        server.start()
        try:
            server.handle_metric_packet(b"ev.c:1|c")
            server.flush()
            rounds = server.telemetry.flushes.snapshot()
            tid = rounds[-1]["trace_id"]
            assert tid
            # flush events stamped with the interval's trace id, and
            # ?trace_id= filters to them
            payload = _http_json(server.http_api,
                                 f"/debug/events?trace_id={tid}")
            kinds = {e["kind"] for e in payload["events"]}
            assert "flush" in kinds
            assert all(e["trace_id"] == tid for e in payload["events"])
            other = _http_json(server.http_api,
                               "/debug/events?trace_id=ffffffff")
            assert other["events"] == []
            # the ledger's closed interval cross-links the same trace
            record = server.ledger.report()["intervals"][-1]
            assert record["trace_id"] == tid
            # the waterfall view carries it too
            waterfall = _http_json(server.http_api,
                                   "/debug/flush?waterfall=1")
            assert waterfall["rounds"][-1]["trace_id"] == tid
        finally:
            server.shutdown()

    def test_flow_report_prints_trace_id(self):
        import importlib.util
        import os
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "flow_report.py")
        spec = importlib.util.spec_from_file_location("flow_report", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        format_report = mod.format_report
        report = {
            "identities": {}, "stage_totals": {}, "stocks": {},
            "intervals": [{"interval": 1, "closed_unix": 1.0,
                           "trace_id": "abc123", "imbalance": {},
                           "stages": {}}],
        }
        text = format_report(report)
        assert "trace=abc123" in text


class TestSamplingKnob:
    def test_rate_zero_disables_recording_and_propagation(self):
        received = []
        ft = ForwardTestServer(received.extend)
        ft.start()
        try:
            server = Server(
                make_config(forward_address=ft.address,
                            trace_self_sample_rate=0.0),
                extra_metric_sinks=[ChannelMetricSink()])
            server.start()
            server.handle_metric_packet(b"off.c:2|c|#veneurglobalonly")
            server.flush()
            server.trace_client.flush()
            assert wait_until(lambda: len(ft.call_metadata) >= 1)
            assert wire.TRACE_KEY not in ft.call_metadata[-1]
            assert len(server.trace_plane.store) == 0
            rounds = server.telemetry.flushes.snapshot()
            assert "trace_id" not in rounds[-1]
            server.shutdown()
        finally:
            ft.stop()

    def test_deterministic_one_in_n(self):
        plane = SelfTracePlane(sample_rate=0.5)
        states = []
        for _ in range(6):
            states.append(plane.interval_sampled)
            plane.roll()
        assert states == [True, False, True, False, True, False]

    def test_follow_rate_survives_odd_trace_ids(self):
        """Regression: _gen_id() makes every trace id odd, so a naive
        `trace_id % period` gate would adopt NOTHING at rate 0.5."""
        from veneur_tpu.trace.store import _gen_id
        plane = SelfTracePlane(sample_rate=0.5)
        adopted = sum(1 for _ in range(400) if plane.follow(_gen_id()))
        assert 120 <= adopted <= 280  # ~half, not zero


class TestRegistryExemplars:
    def test_counters_only_once_and_negotiated(self):
        from veneur_tpu.core.telemetry import Registry
        reg = Registry()
        ex = ExemplarStore()
        ex.capture("pipeline.sample_age", 1.5, trace_id=42, ts=7.0)

        def source(name, tags):
            entry = ex.for_series(name, tags)
            if entry is None:
                return None
            from veneur_tpu.trace.store import (
                render_openmetrics_exemplar)
            return render_openmetrics_exemplar(entry)

        reg.exemplar_source = source
        reg.count("pipeline.sample_age.count", 3.0, ["plane:a"])
        reg.count("pipeline.sample_age.count", 2.0, ["plane:b"])
        reg.gauge("pipeline.sample_age.p99", 1.2, ["plane:a"])
        plain = reg.render_prometheus()
        assert "trace_id=" not in plain  # default: no exemplars
        om = reg.render_prometheus(exemplars=True)
        ex_lines = [ln for ln in om.splitlines() if "trace_id=" in ln]
        assert len(ex_lines) == 1  # once per name, counters only
        assert "_total" in ex_lines[0].split(" ")[0]
        assert "p99" not in ex_lines[0]  # never on a gauge row


@pytest.mark.slow
class TestTracingOverheadSoak:
    """Self-tracing + exemplar capture pinned under 2% of flush wall
    time vs trace_self_sample_rate: 0 (the acceptance guard)."""

    N_KEYS = 1500
    ROUNDS = 30

    def _median_flush_s(self, rate: float) -> float:
        cfg = make_config(trace_self_sample_rate=rate)
        cfg.tpu.counter_capacity = 4096
        cfg.tpu.gauge_capacity = 4096
        cfg.tpu.histo_capacity = 4096
        cfg.tpu.set_capacity = 1024
        server = Server(cfg, extra_metric_sinks=[ChannelMetricSink()])
        pkts = []
        for i in range(self.N_KEYS):
            kind = i % 4
            if kind == 0:
                pkts.append(b"soak.c%d:1|c" % i)
            elif kind == 1:
                pkts.append(b"soak.g%d:2.5|g" % i)
            elif kind == 2:
                pkts.append(b"soak.t%d:3:4:5|ms" % i)
            else:
                pkts.append(b"soak.l%d:6|l" % i)
        try:
            server.handle_packet_batch(pkts)
            server.store.apply_all_pending()
            server.flush()  # compile outside the measured window
            times = []
            for _ in range(self.ROUNDS):
                server.handle_packet_batch(pkts)
                server.store.apply_all_pending()
                t0 = time.perf_counter()
                server.flush()
                times.append(time.perf_counter() - t0)
            times.sort()
            return times[len(times) // 2]
        finally:
            server.shutdown()

    def test_tracing_overhead_under_2pct(self):
        off = self._median_flush_s(rate=0.0)
        on = self._median_flush_s(rate=1.0)
        # 2% of flush wall time, plus a 200µs absolute epsilon so OS
        # scheduling noise on a fast flush can't fail a passing build
        assert on <= off * 1.02 + 0.0002, \
            f"self-tracing overhead {on - off:.6f}s vs base {off:.6f}s"
