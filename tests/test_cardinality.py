"""Cardinality observatory tests: the heavy-hitter tracker (exact on
small, bounded on large, SALSA-style decay), per-tag-key HLL diagnosis,
the /debug/cardinality endpoint shape, capacity-resize events, the
registry-overflow attribution, the proxy's per-destination forwarded-key
estimates, and the cardinality shed-rung storm soak (exact accounting of
rejected mints, zero loss for pre-existing keys, immediate recovery)."""

from __future__ import annotations

import json
import time

import pytest

from veneur_tpu.core.cardinality import (
    MAX_TAG_KEYS, CardinalityAccountant, SpaceSaving, TagCardinality,
)
from veneur_tpu.core.columnstore import CounterTable
from veneur_tpu.core.httpapi import HTTPApi
from veneur_tpu.core.telemetry import Registry
from veneur_tpu.samplers.parser import Parser
from veneur_tpu.util import http as vhttp

from test_server import generate_config, setup_server


def mk_metric(name: str, tags=(), mtype: bytes = b"c", value: float = 1.0):
    out = []
    line = b"%s:%f|%s" % (name.encode(), value, mtype)
    if tags:
        line += b"|#" + ",".join(tags).encode()
    Parser().parse_metric_fast(line, out.append)
    return out[0]


def by_name(metrics):
    out = {}
    for metric in metrics:
        out.setdefault(metric.name, []).append(metric)
    return out


class TestSpaceSaving:
    def test_exact_on_small(self):
        ss = SpaceSaving(capacity=32)
        for i in range(10):
            for _ in range(i + 1):
                rec = ss.get_or_track(f"name.{i}")
                rec.weight += 1
                rec.mints_total += 1
        assert len(ss.records) == 10
        assert ss.evictions == 0
        top = ss.top(3)
        assert [r.name for r in top] == ["name.9", "name.8", "name.7"]
        assert top[0].mints_total == 10
        assert top[0].error == 0.0  # never evicted -> exact

    def test_bounded_on_large(self):
        ss = SpaceSaving(capacity=16)
        # one genuine heavy hitter among a spray of singletons
        for i in range(500):
            rec = ss.get_or_track(f"spray.{i}")
            rec.weight += 1
            if i % 2 == 0:
                heavy = ss.get_or_track("heavy")
                heavy.weight += 1
        assert len(ss.records) <= 16  # hard memory bound
        assert ss.evictions > 0
        top = ss.top(1)[0]
        assert top.name == "heavy"
        # space-saving guarantee: the heavy hitter's score is never
        # underestimated (weight >= true count)
        assert top.weight >= 250

    def test_live_rows_pin_residency(self):
        ss = SpaceSaving(capacity=8)
        owner = ss.get_or_track("owner")
        owner.live_rows = 100
        for i in range(50):
            rec = ss.get_or_track(f"churn.{i}")
            rec.weight += 1
        assert "owner" in ss.records  # live rows outscore churn weight

    def test_decay_releases_quiet_names(self):
        ss = SpaceSaving(capacity=32)
        rec = ss.get_or_track("quiet")
        rec.weight = 0.6
        busy = ss.get_or_track("busy")
        busy.weight = 100.0
        ss.decay(0.5)
        assert "quiet" not in ss.records  # 0.3 < 0.5 and no live rows
        assert ss.records["busy"].weight == pytest.approx(50.0)


class TestTagCardinality:
    def test_estimates_which_tag_explodes(self):
        tc = TagCardinality(max_names=2)
        tc.start("boom")
        for i in range(2000):
            tc.observe("boom", [f"user:u{i}", "region:eu", "flag"])
        report = tc.report("boom")
        est = report["tag_keys"]
        assert est["region"] == 1
        assert est["flag"] == 1  # bare tag -> one distinct (empty) value
        assert abs(est["user"] - 2000) / 2000 < 0.05  # p=14 ~0.8% stderr
        assert tc.report("unknown") is None

    def test_tag_key_bound(self):
        tc = TagCardinality(max_names=1)
        tc.start("wide")
        tc.observe("wide", [f"k{i}:v" for i in range(MAX_TAG_KEYS + 10)])
        report = tc.report("wide")
        assert len(report["tag_keys"]) == MAX_TAG_KEYS
        assert report["tag_keys_overflow"] == 10

    def test_name_slots_bounded_and_idle_released(self):
        tc = TagCardinality(max_names=1)
        tc.start("a")
        tc.start("b")  # over the cap: not tracked
        assert tc.tracked_names() == ["a"]
        for _ in range(6):
            tc.roll_interval()  # idle past TAG_IDLE_INTERVALS
        assert tc.tracked_names() == []
        tc.start("b")  # slot free again
        assert tc.tracked_names() == ["b"]


class TestAccountant:
    def test_hard_limit_exact_accounting(self):
        sheds = []
        acct = CardinalityAccountant(
            hard_limit=10,
            on_shed=lambda fam, n, reason: sheds.append((fam, n, reason)))
        admitted = sum(
            acct.admit_mint("counter", "storm", [f"u:{i}"])
            for i in range(100))
        assert admitted == 10
        assert len(sheds) == 90
        assert all(s == ("counter", 1, "cardinality") for s in sheds)
        # other names are untouched by storm's budget
        assert acct.admit_mint("counter", "calm", ["k:v"])

    def test_soft_limit_degrades_one_in_n(self):
        acct = CardinalityAccountant(soft_limit=10, degraded_keep=0.25)
        admitted = sum(
            acct.admit_mint("counter", "warm", [])
            for i in range(10 + 40))
        # 10 under the limit + exactly 1-in-4 of the 40 past it
        assert admitted == 10 + 10

    def test_recovery_is_immediate_on_roll(self):
        acct = CardinalityAccountant(hard_limit=5)
        for i in range(20):
            acct.admit_mint("counter", "storm", [])
        assert not acct.admit_mint("counter", "storm", [])
        assert acct.limits_report()["over_hard"] == ["storm"]
        acct.roll_interval()  # budgets reset at the flush boundary
        assert acct.limits_report()["over_hard"] == []
        assert acct.admit_mint("counter", "storm", [])

    def test_live_rows_track_mints_and_evictions(self):
        acct = CardinalityAccountant()
        for _ in range(3):
            assert acct.admit_mint("counter", "app.reqs", [])
            acct.note_mint("counter", "app.reqs")
        rec = acct.tracker.records["app.reqs"]
        assert rec.live_rows == 3
        assert rec.families == {"counter": 3}
        acct.note_evicted("counter", ["app.reqs", "app.reqs"])
        assert rec.live_rows == 1
        assert rec.families == {"counter": 1}

    def test_tag_tracking_starts_at_threshold(self):
        acct = CardinalityAccountant(hll_min_mints=5, hll_names=2)
        for i in range(20):
            acct.admit_mint("set", "boom", [f"id:{i}"])
        report = acct.name_report("boom")
        assert report["tracked"]
        # values observed only after tracking started still dominate
        assert report["tags"]["tag_keys"]["id"] >= 10
        rows = dict()
        for name, kind, value, tags in acct.telemetry_rows():
            rows[name] = value
        assert rows["cardinality.tag_tracked_names"] == 1.0
        assert rows["cardinality.names_tracked"] == 1.0


class TestTableIntegration:
    def test_row_for_respects_accountant(self):
        acct = CardinalityAccountant(hard_limit=3)
        t = CounterTable(64)
        t.cardinality = acct
        t.family = "counter"
        rows = [t.intern(mk_metric("storm", [f"u:{i}"])) for i in range(10)]
        assert sum(r >= 0 for r in rows) == 3
        assert sum(r < 0 for r in rows) == 7
        # existing keys always re-intern (updates are never gated)
        assert t.intern(mk_metric("storm", ["u:0"])) == rows[0]
        assert acct.tracker.records["storm"].live_rows == 3
        assert t.minted_total == 3

    def test_eviction_decrements_live_rows(self):
        acct = CardinalityAccountant()
        t = CounterTable(64)
        t.cardinality = acct
        t.family = "counter"
        t.add(mk_metric("fleeting"))
        assert acct.tracker.records["fleeting"].live_rows == 1
        t.snapshot_and_reset()
        t.snapshot_and_reset()
        t.snapshot_and_reset()
        evicted = t.reclaim_idle(2)
        assert evicted and t.tombstoned_total == 1
        assert acct.tracker.records["fleeting"].live_rows == 0


class TestShardedMergeRejection:
    def test_sharded_merges_filter_rejected_mints(self):
        """A cardinality-rejected stub (row_for -> -1) must drop out of
        the sharded import merges — scattering -1 would negative-index
        the LAST device row, corrupting an unrelated series."""
        import numpy as np
        from veneur_tpu.core import sharded_tables
        from veneur_tpu.ops import batch_hll
        devices = sharded_tables.local_shard_devices(2)
        if len(devices) < 2:
            pytest.skip("needs >= 2 local devices (virtual CPU mesh)")
        acct = CardinalityAccountant(hard_limit=1)
        t = sharded_tables.ShardedSetTable(8, 64, devices)
        t.cardinality = acct
        t.family = "set"
        stubs = [mk_metric("storm", ["u:1"], b"s"),
                 mk_metric("storm", ["u:2"], b"s")]  # 2nd mint rejected
        regs = np.zeros((2, batch_hll.M), np.int8)
        regs[:, 7] = 5
        t.merge_batch(stubs, regs)
        assert not t.touched[-1]  # last row untouched (no -1 scatter)
        assert t.touched[0] and len(t.rows) == 1

        th = sharded_tables.ShardedHistoTable(8, 64, devices)
        th.cardinality = CardinalityAccountant(hard_limit=1)
        th.family = "histogram"
        hstubs = [mk_metric("storm", ["u:1"], b"ms"),
                  mk_metric("storm", ["u:2"], b"ms")]
        from veneur_tpu.ops import batch_tdigest
        means = np.zeros((2, batch_tdigest.C), np.float32)
        weights = np.zeros((2, batch_tdigest.C), np.float32)
        weights[:, 0] = 1.0
        th.merge_batch(hstubs, means, weights, [0.0, 0.0], [1.0, 1.0],
                       [1.0, 1.0])
        assert not th.touched[-1]
        assert th.touched[0] and len(th.rows) == 1


class TestRegistryOverflowAttribution:
    def test_dropped_series_tagged_by_name(self):
        reg = Registry(max_series=2)
        reg.count("a", 1)
        reg.count("b", 1)
        reg.count("noisy", 1)   # over the cap
        reg.count("noisy", 1)
        reg.gauge("other", 2.0)
        assert reg.series_dropped == 3
        assert reg.dropped_by_name == {"noisy": 2, "other": 1}
        text = reg.render_prometheus()
        assert 'veneur_telemetry_series_dropped_by_name_total' \
            '{name="noisy"} 2' in text
        assert reg.snapshot()["series_dropped_by_name"]["noisy"] == 2

    def test_attribution_itself_is_bounded(self):
        from veneur_tpu.core import telemetry as tmod
        reg = Registry(max_series=1)
        reg.count("keep", 1)
        for i in range(tmod.MAX_DROPPED_NAMES + 25):
            reg.count(f"spray.{i}", 1)
        assert len(reg.dropped_by_name) == tmod.MAX_DROPPED_NAMES + 1
        assert reg.dropped_by_name["_other"] == 25


class TestServerObservatory:
    def test_resize_emits_event_and_metrics(self):
        server, _observer = setup_server()
        try:
            cap = server.store.counters.capacity
            for i in range(cap + 8):
                server.handle_metric_packet(b"grow.%d:1|c" % i)
            events = server.telemetry.events.snapshot(
                kind="columnstore_resize")
            assert len(events) == 1
            ev = events[0]
            assert ev["family"] == "counter"
            assert ev["old_capacity"] == cap
            assert ev["new_capacity"] == cap * 2
            assert ev["duration_s"] > 0
            # the jit retrace for the new capacity lands on the next
            # batch apply and is timed + recorded as its own event
            server.store.counters.apply_pending()
            rec = server.telemetry.events.snapshot(
                kind="columnstore_recompile")
            assert len(rec) == 1 and rec[0]["duration_s"] > 0
            text = server.telemetry.registry.render_prometheus()
            assert ('veneur_columnstore_resize_total'
                    '{family="counter"} 1') in text
            assert 'veneur_columnstore_resize_seconds_total' in text
            assert 'veneur_columnstore_row_capacity' in text
        finally:
            server.shutdown()

    def test_cardinality_report_shape(self):
        server, observer = setup_server(
            cardinality_hard_limit=1000, cardinality_hll_min_mints=2)
        try:
            for i in range(32):
                server.handle_metric_packet(b"hot.name:1|c|#user:u%d" % i)
            server.handle_metric_packet(b"cold.name:7|g")
            report = server.cardinality_report(top=5)
            assert report["total_names"] >= 2
            top = report["top"]
            assert top[0]["name"] == "hot.name"
            assert top[0]["live_rows"] == 32
            assert top[0]["mints_interval"] == 32
            assert "tags" in top[0]  # tag tracking kicked in at 2 mints
            assert top[0]["families"] == {"counter": 32}
            assert report["limits"]["hard_limit"] == 1000
            assert report["tables"]["counter"]["live_rows"] >= 32
            # drill-down merges exact store rows with the tracker record
            detail = server.cardinality_report(name="hot.name")
            assert detail["tracked"] and detail["live_rows"] == 32
            # tracking starts at the 2nd mint, so >= 31 values observed
            assert abs(detail["tags"]["tag_keys"]["user"] - 31) <= 2
            # mint RATE appears after one interval rollover
            server.flush()
            observer.wait_flush()
            detail = server.cardinality_report(name="hot.name")
            assert detail["mints_last_interval"] == 32
            assert detail["mint_rate_per_s"] > 0
        finally:
            server.shutdown()

    def test_hard_capped_offender_still_tops_report(self):
        """A storm the hard limit is successfully capping has FEW
        admitted rows — the report must still surface it (by mint
        activity), not hide it behind a large steady keyset."""
        server, _observer = setup_server(cardinality_hard_limit=5,
                                         cardinality_hll_min_mints=8)
        try:
            for i in range(40):
                server.handle_metric_packet(b"steady.big:1|c|#h:%d" % i)
            for i in range(200):
                server.handle_metric_packet(b"storm.capped:1|c|#u:%d" % i)
            report = server.cardinality_report(top=2)
            names = [r["name"] for r in report["top"]]
            assert names[0] == "storm.capped"  # 5 rows but 200 mints
            row = report["top"][0]
            assert row["live_rows"] == 5
            assert row["mints_interval"] == 200
            assert "tags" in row  # the diagnosis rides along
        finally:
            server.shutdown()

    def test_debug_cardinality_endpoint(self):
        server, _observer = setup_server(cardinality_hll_min_mints=2)
        api = HTTPApi(server.config, server=server, address="127.0.0.1:0")
        api.start()
        try:
            for i in range(16):
                server.handle_metric_packet(b"api.storm:1|c|#k:v%d" % i)
            host, port = api.address
            status, body = vhttp.get(
                f"http://{host}:{port}/debug/cardinality?top=1")
            assert status == 200
            payload = json.loads(body)
            assert len(payload["top"]) == 1
            assert payload["top"][0]["name"] == "api.storm"
            assert payload["top"][0]["live_rows"] == 16
            assert "tables" in payload and "limits" in payload
            status, body = vhttp.get(
                f"http://{host}:{port}/debug/cardinality?name=api.storm")
            detail = json.loads(body)
            assert detail["name"] == "api.storm"
            assert abs(detail["tags"]["tag_keys"]["k"] - 15) <= 2
        finally:
            api.stop()
            server.shutdown()


class TestProxyForwardedKeys:
    """The proxy side of the observatory: per-destination forwarded-key
    HLL estimates on /metrics and /debug/cardinality."""

    @staticmethod
    def _mkmetric(name, tags=()):
        from veneur_tpu.forward.protos import metric_pb2
        pbm = metric_pb2.Metric(name=name, type=metric_pb2.Counter,
                                scope=metric_pb2.Global)
        pbm.tags.extend(tags)
        pbm.counter.value = 1
        return pbm

    def test_per_destination_key_estimates(self):
        from veneur_tpu.proxy.proxy import create_static_proxy
        from veneur_tpu.testing.forwardtest import ForwardTestServer
        received = []
        backend = ForwardTestServer(received.append)
        backend.start()
        proxy = create_static_proxy([backend.address])
        proxy.start()
        try:
            for _round in range(2):  # repeats must not inflate distinct
                for i in range(64):
                    proxy.handle_metric(
                        self._mkmetric("proxied.reqs", [f"u:{i}"]))
            report = proxy.cardinality_report()
            dest = report["destinations"][0]
            assert dest["address"] == backend.address
            assert abs(dest["forwarded_keys_estimate"] - 64) <= 3
            assert report["routing"]["received_total"] == 128
            rows = [r for r in proxy.telemetry_rows()
                    if r[0] == "proxy.dest.forwarded_keys"]
            assert len(rows) == 1
            assert abs(rows[0][2] - 64) <= 3
            # name filter drills to one destination
            assert proxy.cardinality_report(
                name="no.such:1234")["destinations"] == []
        finally:
            proxy.stop()
            backend.stop()


@pytest.mark.storm
class TestStormSoak:
    """The shed-rung acceptance soak: a tag explosion past
    cardinality_hard_limit is rejected with exact accounting, never
    touches pre-existing keys, and recovers the moment it stops."""

    STORM = 600
    LIMIT = 50
    PRE = 12

    def test_storm_shed_exact_zero_loss_and_recovery(self):
        server, observer = setup_server(
            cardinality_hard_limit=self.LIMIT,
            cardinality_hll_min_mints=16)
        try:
            # interval 1: a healthy steady keyset
            for i in range(self.PRE):
                server.handle_metric_packet(b"steady.reqs:1|c|#h:%d" % i)
            server.flush()
            assert len(observer.wait_flush()) == self.PRE

            # interval 2: the storm, interleaved with steady updates
            for i in range(self.STORM):
                server.handle_metric_packet(b"bad.tags:1|c|#u:%d" % i)
                if i % 50 == 0:
                    for j in range(self.PRE):
                        server.handle_metric_packet(
                            b"steady.reqs:1|c|#h:%d" % j)

            # exact accounting: every rejected mint is one shed sample
            rejected = self.STORM - self.LIMIT
            assert server.overload.shed_total == {
                "counter|cardinality": rejected}
            report = server.cardinality_report(name="bad.tags")
            assert report["mints_interval"] == self.STORM
            assert report["live_rows"] == self.LIMIT
            # the diagnosis names the exploding tag
            est = report["tags"]["tag_keys"]["u"]
            assert abs(est - self.STORM) / self.STORM < 0.05

            server.flush()
            got = by_name(observer.wait_flush())
            # zero loss for pre-existing keys: every steady row kept
            # every update (12 rows x value 12 = the 600/50 interleaves)
            assert len(got["steady.reqs"]) == self.PRE
            assert all(m.value == self.STORM / 50
                       for m in got["steady.reqs"])
            assert len(got["bad.tags"]) == self.LIMIT

            # the ladder edges are on the flight recorder
            kinds = [e["kind"] for e in server.telemetry.events.snapshot()]
            assert "cardinality_hard_limit" in kinds
            assert "cardinality_recovered" in kinds

            # recovery: the flush rolled the interval -> new keys mint
            # again immediately, and sheds do not move
            for i in range(self.STORM, self.STORM + 20):
                server.handle_metric_packet(b"bad.tags:1|c|#u:%d" % i)
            assert server.overload.shed_total == {
                "counter|cardinality": rejected}
            server.flush()
            got = by_name(observer.wait_flush())
            assert len(got["bad.tags"]) == 20
            # /metrics carries the shed with the cardinality reason tag
            text = server.telemetry.registry.render_prometheus()
            assert (f'veneur_ingest_shed_total{{class="counter",'
                    f'reason="cardinality"}} {rejected}') in text
        finally:
            server.shutdown()
