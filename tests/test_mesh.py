"""Multi-device merge-plane tests over the virtual 8-device CPU mesh.

Validates veneur_tpu.parallel.mesh — the ICI collective equivalent of the
reference's forward/import merge semantics (reference worker.go:410-467):
counter psum exactness, gauge last-set-wins, HLL register pmax against the
scalar oracle, and t-digest key-sharded all_to_all+recompress quantile accuracy within
the reference's own test tolerance (reference tdigest/histo_test.go:95-176,
epsilon 0.02 in uniform-value space).
"""

import jax
import numpy as np
import pytest

from veneur_tpu.ops import batch_hll, batch_tdigest, hll_ref, tdigest_ref
from veneur_tpu.parallel import mesh as pmesh

N_DEV = 8
NUM_KEYS = 64


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices (virtual CPU mesh)")
    return pmesh.make_mesh(N_DEV)


def _merged(mesh, state, batches):
    state = pmesh.apply_shard_batches(state, batches)
    return pmesh.merge_shards(mesh, state)


class TestCounterMerge:
    def test_psum_exactness(self, mesh):
        batch = 512
        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, batch, seed=11)
        merged = _merged(mesh, state, batches)

        want = np.zeros(NUM_KEYS, np.float64)
        contrib = np.trunc(
            np.asarray(batches["c_vals"], np.float64)
            / np.asarray(batches["c_rates"], np.float64))
        np.add.at(want, np.asarray(batches["c_rows"]).reshape(-1),
                  contrib.reshape(-1))
        got = np.asarray(merged["counters"], np.float64)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_full_step_matches_manual(self, mesh):
        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, 128, seed=3)
        _, merged = pmesh.full_step(mesh, state, batches)
        manual = _merged(
            mesh, pmesh.init_sharded_state(mesh, NUM_KEYS), batches)
        np.testing.assert_allclose(np.asarray(merged["counters"]),
                                   np.asarray(manual["counters"]))


class TestGaugeMerge:
    def test_last_set_shard_wins(self, mesh):
        """Each shard sets a disjoint-but-overlapping key range; the merged
        value for a key must come from the highest shard index that set it,
        and keys no shard set must stay unset."""
        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, 16, seed=5)
        # shard s writes value 1000*s+k to keys [0, 8*(s+1)) — shard 7
        # covers the most keys; key k's winner is the highest shard with
        # 8*(s+1) > k
        rows = np.full((N_DEV, 16), 2**31 - 1, np.int32)
        vals = np.zeros((N_DEV, 16), np.float32)
        for s in range(N_DEV):
            span = min(16, 8 * (s + 1))
            rows[s, :span] = np.arange(span)
            vals[s, :span] = 1000 * s + np.arange(span)
        batches["g_rows"] = rows
        batches["g_vals"] = vals
        merged = _merged(mesh, state, batches)

        got_vals = np.asarray(merged["gauges"]["value"])
        got_set = np.asarray(merged["gauges"]["set"])
        for k in range(16):
            assert got_set[k]
            assert got_vals[k] == pytest.approx(1000 * (N_DEV - 1) + k)
        # rows 16..: nothing wrote them
        assert not got_set[16:].any()

    def test_single_shard_writer(self, mesh):
        """A key only shard 2 writes must surface shard 2's value."""
        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, 4, seed=6)
        rows = np.full((N_DEV, 4), 2**31 - 1, np.int32)
        vals = np.zeros((N_DEV, 4), np.float32)
        rows[2, 0] = 42
        vals[2, 0] = 7.5
        batches["g_rows"] = rows
        batches["g_vals"] = vals
        merged = _merged(mesh, state, batches)
        assert np.asarray(merged["gauges"]["set"])[42]
        assert np.asarray(merged["gauges"]["value"])[42] == pytest.approx(7.5)


class TestHLLMerge:
    def test_pmax_matches_scalar_oracle(self, mesh):
        """Shard-merged registers must equal the elementwise max of every
        shard's registers, and the estimate must match the scalar oracle
        computed from those merged registers."""
        rng = np.random.default_rng(17)
        batch = 256
        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, batch, seed=17)
        merged = _merged(mesh, state, batches)

        # oracle: scatter-max on host over all shards
        want = np.zeros((NUM_KEYS, batch_hll.M), np.int8)
        rows = np.asarray(batches["s_rows"]).reshape(-1)
        idx = np.asarray(batches["s_idx"]).reshape(-1)
        rho = np.asarray(batches["s_rho"]).reshape(-1)
        np.maximum.at(want, (rows, idx), rho.astype(np.int8))
        got = np.asarray(merged["sets"])
        np.testing.assert_array_equal(got, want)

        est = np.asarray(batch_hll.estimate(merged["sets"]))
        for k in rng.choice(NUM_KEYS, 8, replace=False):
            oracle = hll_ref.estimate_from_registers(want[k])
            assert est[k] == pytest.approx(oracle, rel=1e-3)

    def test_true_cardinality_accuracy(self, mesh):
        """Distinct members spread over shards: merged estimate within the
        ~0.8% p14 standard error (3 sigma) of the true cardinality."""
        n_members = 20_000
        members = [b"member-%d" % i for i in range(n_members)]
        hashes = [hll_ref.hash_member(mb) for mb in members]
        pos = np.array([hll_ref.pos_val(h) for h in hashes], np.int64)
        per = n_members // N_DEV
        rows = np.zeros((N_DEV, per), np.int32)  # all into key 0
        idx = np.zeros((N_DEV, per), np.int32)
        rho = np.zeros((N_DEV, per), np.int32)
        for s in range(N_DEV):
            sl = slice(s * per, (s + 1) * per)
            idx[s] = pos[sl, 0]
            rho[s] = pos[sl, 1]
        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, per, seed=1)
        batches["s_rows"], batches["s_idx"], batches["s_rho"] = rows, idx, rho
        merged = _merged(mesh, state, batches)
        est = float(np.asarray(batch_hll.estimate(merged["sets"]))[0])
        assert est == pytest.approx(n_members, rel=0.03)


class TestDigestMerge:
    def test_keysharded_recompress_quantiles(self, mesh):
        """Uniform samples split across shards: merged quantiles within
        the reference's 0.02 uniform-space tolerance of the true values
        and of a scalar reference digest fed all samples."""
        rng = np.random.default_rng(23)
        per = 2048
        data = rng.uniform(0.0, 1.0, (N_DEV, per)).astype(np.float32)

        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, per, seed=2)
        batches["h_rows"] = np.zeros((N_DEV, per), np.int32)
        batches["h_vals"] = data
        batches["h_wts"] = np.ones((N_DEV, per), np.float32)
        batches["h_slots"] = np.stack([
            batch_tdigest.batch_slots(
                batches["h_rows"][i], batches["h_vals"][i],
                batches["h_wts"][i], NUM_KEYS)
            for i in range(N_DEV)])
        merged = _merged(mesh, state, batches)

        ps = (0.25, 0.5, 0.9, 0.99)
        out = batch_tdigest.flush_quantiles(merged["histos"], ps)
        ref = tdigest_ref.MergingDigest()
        for v in data.reshape(-1):
            ref.add(float(v))
        allv = np.sort(data.reshape(-1))
        for j, q in enumerate(ps):
            got = float(out["quantiles"][0, j])
            true = float(allv[int(q * (len(allv) - 1))])
            assert got == pytest.approx(true, abs=0.02), q
            assert got == pytest.approx(ref.quantile(q), abs=0.02), q
        assert float(out["count"][0]) == pytest.approx(N_DEV * per, rel=1e-3)
        assert float(out["min"][0]) == pytest.approx(float(allv[0]), abs=1e-6)
        assert float(out["max"][0]) == pytest.approx(float(allv[-1]), abs=1e-6)

    def test_merge_matches_single_shard_ingest(self, mesh):
        """Splitting a stream over 8 shards then merging must agree with
        ingesting the whole stream into one digest state."""
        rng = np.random.default_rng(29)
        per = 1024
        data = rng.normal(100.0, 15.0, (N_DEV, per)).astype(np.float32)

        state = pmesh.init_sharded_state(mesh, NUM_KEYS)
        batches = pmesh.make_shard_batches(N_DEV, NUM_KEYS, per, seed=4)
        batches["h_rows"] = np.zeros((N_DEV, per), np.int32)
        batches["h_vals"] = data
        batches["h_wts"] = np.ones((N_DEV, per), np.float32)
        batches["h_slots"] = np.stack([
            batch_tdigest.batch_slots(
                batches["h_rows"][i], batches["h_vals"][i],
                batches["h_wts"][i], NUM_KEYS)
            for i in range(N_DEV)])
        merged = _merged(mesh, state, batches)

        single = batch_tdigest.init_state(NUM_KEYS)
        single = batch_tdigest.apply_batch(
            single, np.zeros(N_DEV * per, np.int32), data.reshape(-1),
            np.ones(N_DEV * per, np.float32))

        ps = (0.5, 0.9, 0.99)
        got = batch_tdigest.flush_quantiles(merged["histos"], ps)
        want = batch_tdigest.flush_quantiles(single, ps)
        for j in range(len(ps)):
            # both are approximations of the same stream; they must agree
            # within twice the documented quantile tolerance (normal data,
            # sigma 15 => value-space slack scales with sigma)
            assert float(got["quantiles"][0, j]) == pytest.approx(
                float(want["quantiles"][0, j]), abs=2 * 0.02 * 15)
        assert float(got["count"][0]) == pytest.approx(
            float(want["count"][0]), rel=1e-3)
