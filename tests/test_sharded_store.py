"""Live multi-device sharding: a server configured with tpu.shards=8 must
produce the same flush output as a single-device server over the same
traffic — the one-host-N-chip deployment as a config, not a demo (the
TPU-native replacement for the reference's worker sharding + forward tree,
reference server.go:1016, flusher.go:516-591)."""

import numpy as np
import pytest

from veneur_tpu.config import Config
from veneur_tpu.core.columnstore import ColumnStore
from veneur_tpu.core.server import Server
from veneur_tpu.sinks.channel import ChannelMetricSink


def _config(shards: int) -> Config:
    cfg = Config()
    cfg.interval = 60.0
    cfg.num_readers = 1
    cfg.statsd_listen_addresses = []
    cfg.percentiles = [0.5, 0.9, 0.99]
    cfg.tpu.counter_capacity = 256
    cfg.tpu.gauge_capacity = 256
    cfg.tpu.histo_capacity = 256
    cfg.tpu.set_capacity = 128
    cfg.tpu.batch_cap = 128  # small cap -> many batch dispatches round-robin
    cfg.tpu.shards = shards
    return cfg.apply_defaults()


def _traffic(server: Server) -> None:
    rng = np.random.default_rng(99)
    for i in range(40):
        for _ in range(8):
            v = rng.normal(100, 15)
            server.handle_metric_packet(
                b"sh.timer.%d:%.3f|ms" % (i % 10, v))
            server.handle_metric_packet(
                b"sh.set.%d:user%d|s" % (i % 5, rng.integers(0, 500)))
            server.handle_metric_packet(b"sh.count:2|c")
    server.store.apply_all_pending()


def _flush_map(server: Server, observer: ChannelMetricSink):
    server.flush()
    return {m.name: m.value for m in observer.wait_flush()}


class TestShardedServerEquivalence:
    def test_flush_matches_single_device(self):
        single, obs1 = Server(_config(1), extra_metric_sinks=[
            s1 := ChannelMetricSink()]), None
        sharded = Server(_config(8), extra_metric_sinks=[
            s8 := ChannelMetricSink()])
        # confirm the sharded store actually took the sharded path —
        # with digest routing ALL five families partition over the mesh
        from veneur_tpu.core.sharded_tables import (
            ShardedCounterTable, ShardedGaugeTable, ShardedHistoTable,
            ShardedLLHistTable, ShardedSetTable)
        assert isinstance(sharded.store.histos, ShardedHistoTable)
        assert isinstance(sharded.store.sets, ShardedSetTable)
        assert isinstance(sharded.store.counters, ShardedCounterTable)
        assert isinstance(sharded.store.gauges, ShardedGaugeTable)
        assert isinstance(sharded.store.llhists, ShardedLLHistTable)
        assert len(sharded.store.histos._devices) == 8
        assert sharded.store.shard_plane is not None
        assert sharded.store.shard_plane.routing == "digest"

        _traffic(single)
        _traffic(sharded)
        got1 = _flush_map(single, s1)
        got8 = _flush_map(sharded, s8)

        assert set(got1) == set(got8)
        for name in got1:
            v1, v8 = got1[name], got8[name]
            if ".50percentile" in name or ".9" in name:
                # both approximate the same stream; sharding reorders
                # batch boundaries, so allow the documented quantile slack
                assert v8 == pytest.approx(v1, rel=0.05, abs=1.5), name
            else:
                # counts, sums, min/max, set estimates: exact or near-exact
                assert v8 == pytest.approx(v1, rel=1e-3), name

    def test_set_estimates_exact_across_shards(self):
        """HLL register max is associative: the sharded estimate must be
        bit-identical to single-device for identical member streams."""
        store1 = ColumnStore(set_capacity=64, batch_cap=32)
        store8 = ColumnStore(set_capacity=64, batch_cap=32, shard_devices=8)
        from veneur_tpu.samplers.parser import Parser
        parser = Parser()
        for i in range(300):
            pkt = b"sh.ex.set:m%d|s" % (i % 211)
            parser.parse_metric_fast(pkt, store1.process)
            parser.parse_metric_fast(pkt, store8.process)
        store1.apply_all_pending()
        store8.apply_all_pending()
        est1, regs1, touched1, _ = store1.sets.snapshot_and_reset()
        est8, regs8, touched8, _ = store8.sets.snapshot_and_reset()
        np.testing.assert_array_equal(touched1, touched8)
        # single-device registers come from the lazy per-row provider;
        # sharded stays a dense array — compare row by row
        for row in np.flatnonzero(touched1):
            np.testing.assert_array_equal(regs1[row], regs8[row])
        np.testing.assert_allclose(
            est1[touched1[: est1.shape[0]]], est8[touched8[: est8.shape[0]]])

    def test_state_resets_between_intervals(self):
        store = ColumnStore(histo_capacity=64, set_capacity=64,
                            batch_cap=32, shard_devices=4)
        from veneur_tpu.samplers.parser import Parser
        parser = Parser()
        for i in range(100):
            parser.parse_metric_fast(b"sh.r.t:%d|ms" % i, store.process)
        store.apply_all_pending()
        out, _, touched, _ = store.histos.snapshot_and_reset((0.5,))
        row = int(np.nonzero(touched)[0][0])
        assert out["count"][row] == pytest.approx(100.0)
        # second interval with no samples: everything zeroed
        out2, _, touched2, _ = store.histos.snapshot_and_reset((0.5,))
        assert not touched2.any()
        assert float(out2["count"][row]) == 0.0

    def test_capacity_growth_while_sharded(self):
        store = ColumnStore(histo_capacity=8, set_capacity=8,
                            batch_cap=16, shard_devices=4)
        from veneur_tpu.samplers.parser import Parser
        parser = Parser()
        # intern far beyond initial capacity to force grow on both families
        for i in range(40):
            parser.parse_metric_fast(b"grow.t.%d:5|ms" % i, store.process)
            parser.parse_metric_fast(b"grow.s.%d:x|s" % i, store.process)
        store.apply_all_pending()
        out, _, touched, _ = store.histos.snapshot_and_reset((0.5,))
        assert int(touched.sum()) == 40
        counts = out["count"][: len(touched)][touched[: out["count"].shape[0]]]
        np.testing.assert_allclose(counts, 1.0)
        est, _, stouched, _ = store.sets.snapshot_and_reset()
        assert int(stouched.sum()) == 40
        np.testing.assert_allclose(est[stouched[: est.shape[0]]], 1.0,
                                   rtol=1e-2)


class TestRoundRobinEscapeHatch:
    def test_roundrobin_shards_only_sketch_families(self):
        """The legacy routing mode keeps the scalar/llhist families
        single-device (rotation destroys gauge ordering) while the
        histogram/set families still shard."""
        from veneur_tpu.core.columnstore import (CounterTable, GaugeTable,
                                                 LLHistTable)
        from veneur_tpu.core.sharded_tables import (ShardedHistoTable,
                                                    ShardedSetTable)
        store = ColumnStore(histo_capacity=64, set_capacity=64,
                            batch_cap=32, shard_devices=4,
                            shard_routing="roundrobin")
        assert isinstance(store.histos, ShardedHistoTable)
        assert isinstance(store.sets, ShardedSetTable)
        assert type(store.counters) is CounterTable
        assert type(store.gauges) is GaugeTable
        assert type(store.llhists) is LLHistTable
        from veneur_tpu.samplers.parser import Parser
        parser = Parser()
        for i in range(100):
            parser.parse_metric_fast(b"rr.t:%d|ms" % i, store.process)
        store.apply_all_pending()
        out, _, touched, _ = store.histos.snapshot_and_reset((0.5,))
        row = int(np.nonzero(touched)[0][0])
        assert out["count"][row] == pytest.approx(100.0)


class TestShardedExport:
    def test_sharded_export_matches_single_device(self):
        """The forwarding export (fused flush) across shards must carry
        the same digest mass as single-device: identical per-row weight
        totals, weighted means, and min/max."""
        store1 = ColumnStore(histo_capacity=128, batch_cap=64)
        store8 = ColumnStore(histo_capacity=128, batch_cap=64,
                             shard_devices=8)
        from veneur_tpu.core.sharded_tables import ShardedHistoTable
        assert isinstance(store8.histos, ShardedHistoTable), \
            "sharded path not taken (virtual mesh unavailable?)"
        from veneur_tpu.samplers.parser import Parser
        parser = Parser()
        rng = np.random.default_rng(17)
        for i in range(600):
            pkt = b"sh.exp.t%d:%.3f|ms" % (i % 29, rng.normal(100, 15))
            parser.parse_metric_fast(pkt, store1.process)
            parser.parse_metric_fast(pkt, store8.process)
        store1.apply_all_pending()
        store8.apply_all_pending()
        out1, exp1, touched1, _ = store1.histos.snapshot_and_reset(
            (0.5,), need_export=True)
        out8, exp8, touched8, _ = store8.histos.snapshot_and_reset(
            (0.5,), need_export=True)
        np.testing.assert_array_equal(touched1, touched8)
        m1, w1, min1, max1, r1 = exp1
        m8, w8, min8, max8, r8 = exp8
        rows = np.flatnonzero(touched1)
        # digest mass and moments are conserved exactly; centroid
        # placement may differ (shards reorder batch boundaries)
        np.testing.assert_allclose(w8[rows].sum(axis=-1),
                                   w1[rows].sum(axis=-1), rtol=1e-5)
        np.testing.assert_allclose(
            (m8[rows] * w8[rows]).sum(axis=-1),
            (m1[rows] * w1[rows]).sum(axis=-1), rtol=1e-4)
        np.testing.assert_allclose(min8[rows], min1[rows], rtol=1e-6)
        np.testing.assert_allclose(max8[rows], max1[rows], rtol=1e-6)
        np.testing.assert_allclose(r8[rows], r1[rows], rtol=1e-5)
