"""Circllhist log-linear histogram family invariants.

The family's contract, pinned here:
- binning brackets every finite value (reference = device: same host
  code path);
- quantile error is bounded by one bin width;
- merges are exact register additions — associative, commutative, and
  bit-identical through the forward plane (local -> global merge equals
  a single node that saw every sample, the acceptance pin);
- carryover of failed forward intervals is lossless (register sums),
  including under the PR-2 chaos soak.
"""

from __future__ import annotations

import numpy as np
import pytest

from veneur_tpu.core.columnstore import ColumnStore
from veneur_tpu.core.flusher import (
    ForwardableState, flush_columnstore, flush_columnstore_batch)
from veneur_tpu.ops import batch_llhist, llhist_ref
from veneur_tpu.samplers import metrics as m
from veneur_tpu.samplers.metrics import HistogramAggregates, MetricType
from veneur_tpu.samplers.parser import Parser

PCTS = (0.5, 0.9, 0.99)
AGGS = HistogramAggregates.from_names(["min", "max", "count"])


def _mk_store(**kw):
    kw.setdefault("llhist_capacity", 64)
    return ColumnStore(counter_capacity=64, gauge_capacity=64,
                       histo_capacity=64, set_capacity=32, batch_cap=128,
                       **kw)


def _feed(store, lines):
    p = Parser()
    for line in lines:
        p.parse_metric_fast(line, store.process)
    store.apply_all_pending()


class TestBinning:
    def test_bins_bracket_values(self):
        rng = np.random.default_rng(0)
        vals = np.concatenate([
            rng.lognormal(0, 4, 2000),
            -rng.lognormal(0, 4, 2000),
            rng.uniform(-1000, 1000, 1000),
        ])
        idx = llhist_ref.bin_index(vals)
        in_range = (np.abs(vals) >= llhist_ref.MIN_MAG) & (
            np.abs(vals) < llhist_ref.MAX_MAG)
        left = llhist_ref.BIN_LEFT[idx[in_range]]
        width = llhist_ref.BIN_WIDTH[idx[in_range]]
        v = vals[in_range]
        assert np.all(v >= left - 1e-12 * np.abs(v))
        assert np.all(v <= left + width + 1e-12 * np.abs(v))

    def test_relative_bin_width_bounded(self):
        # log-linear guarantee: width / |lower edge| <= 1/10
        nz = llhist_ref.BIN_WIDTH > 0
        rel = llhist_ref.BIN_WIDTH[nz] / np.abs(llhist_ref.BIN_LEFT[nz])
        assert np.all(rel <= 0.1 + 1e-12)

    def test_zero_and_out_of_range(self):
        assert llhist_ref.bin_index(0.0) == llhist_ref.ZERO_BIN
        assert llhist_ref.bin_index(1e-30) == llhist_ref.ZERO_BIN
        top_pos = llhist_ref.bin_index(1e30)
        assert llhist_ref.BIN_LEFT[top_pos] == pytest.approx(
            99 * 10.0 ** (llhist_ref.EXP_MAX - 1))
        assert llhist_ref.clamped_mask([1e30, 1e-30, 5.0]).tolist() == \
            [True, True, False]

    def test_sign_symmetry(self):
        vals = np.array([0.123, 7.7, 42.0, 9999.0])
        pos = llhist_ref.bin_index(vals)
        neg = llhist_ref.bin_index(-vals)
        assert np.array_equal(
            neg - pos, np.full(4, llhist_ref.MANT * llhist_ref.NEXP))

    def test_scalar_matches_vector(self):
        vals = [0.0, 1.0, -2.5, 3e7, 1e-9]
        vec = llhist_ref.bin_index(vals)
        for v, i in zip(vals, vec):
            assert llhist_ref.bin_index(v) == i


class TestQuantiles:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_error_bounded_by_one_bin_width(self, seed):
        rng = np.random.default_rng(seed)
        samples = rng.lognormal(rng.uniform(-2, 4), rng.uniform(0.3, 2),
                                5000)
        if seed % 2:
            samples = np.concatenate([samples, -samples[:1000]])
        h = llhist_ref.LLHist()
        h.insert_many(samples)
        for p in (0.0, 0.1, 0.5, 0.9, 0.99, 1.0):
            true = np.quantile(samples, p)
            got = h.quantile(p)
            width = llhist_ref.BIN_WIDTH[llhist_ref.bin_index(true)]
            assert abs(got - true) <= width + 1e-9, (p, got, true)

    def test_empty_reads_zero(self):
        h = llhist_ref.LLHist()
        assert h.quantile(0.5) == 0.0
        assert h.count() == 0 and h.sum() == 0.0

    def test_batch_readout_matches_reference(self):
        rng = np.random.default_rng(4)
        samples = rng.lognormal(2, 1, 4000)
        rows = rng.integers(0, 50, 4000).astype(np.int32)
        bins, wts = batch_llhist.bin_batch_host(samples)
        state = batch_llhist.apply_batch(
            batch_llhist.init_state(64), rows, bins, wts)
        out = batch_llhist.flush_packed(state, PCTS)
        ref = np.zeros((64, llhist_ref.BINS), np.int64)
        np.add.at(ref, (rows, bins), wts)
        assert np.array_equal(
            np.asarray(state)[:, :llhist_ref.BINS], ref)
        q = np.asarray(out["quantiles"])
        for r in range(50):
            np.testing.assert_allclose(
                q[r], llhist_ref.quantiles(ref[r], PCTS), rtol=1e-5)
            assert np.asarray(out["count"])[r] == ref[r].sum()


class TestMergeInvariants:
    def test_merge_associative_commutative_fuzz(self):
        rng = np.random.default_rng(5)
        chunks = [rng.lognormal(1, 1.5, rng.integers(10, 500))
                  for _ in range(6)]
        hists = []
        for c in chunks:
            h = llhist_ref.LLHist()
            h.insert_many(c)
            hists.append(h)

        def merged(order):
            acc = llhist_ref.LLHist()
            for i in order:
                acc.merge(hists[i])
            return acc.bins

        base = merged(range(6))
        assert np.array_equal(base, merged([5, 3, 1, 0, 4, 2]))
        assert np.array_equal(base, merged([2, 4, 0, 1, 3, 5]))
        # associativity: ((a+b)+c) == (a+(b+c)) via pairwise trees
        ab = llhist_ref.LLHist(hists[0].bins + hists[1].bins)
        ab.merge(hists[2])
        bc = llhist_ref.LLHist(hists[1].bins + hists[2].bins)
        bc.merge(hists[0])
        assert np.array_equal(ab.bins, bc.bins)
        # and against the one-shot reference over the union stream
        union = llhist_ref.LLHist()
        union.insert_many(np.concatenate(chunks))
        assert np.array_equal(merged(range(6)), union.bins)

    def test_split_ingest_equals_union_ingest(self):
        rng = np.random.default_rng(6)
        samples = rng.lognormal(3, 1, 2000)
        lines = [b"mrg.k:%.5f|l" % v for v in samples]
        whole, left, right = _mk_store(), _mk_store(), _mk_store()
        _feed(whole, lines)
        _feed(left, lines[:1000])
        _feed(right, lines[1000:])
        snap = {}
        for name, st in (("whole", whole), ("left", left),
                         ("right", right)):
            out, bins, touched, meta = st.llhists.snapshot_and_reset(PCTS)
            snap[name] = bins[0]
        assert np.array_equal(snap["whole"], snap["left"] + snap["right"])


class TestWire:
    def test_llhistwire_roundtrip_fuzz(self):
        from veneur_tpu.forward import llhistwire
        rng = np.random.default_rng(7)
        for _ in range(30):
            bins = np.zeros(llhist_ref.BINS, np.int64)
            n = int(rng.integers(0, 200))
            if n:
                idx = rng.choice(llhist_ref.BINS, n, replace=False)
                bins[idx] = rng.integers(1, 1 << 48, n)
            assert np.array_equal(
                llhistwire.unmarshal(llhistwire.marshal(bins)), bins)
        dense = rng.integers(0, 5, llhist_ref.BINS).astype(np.int64)
        assert np.array_equal(
            llhistwire.unmarshal(llhistwire.marshal(dense)), dense)

    def test_proto_roundtrip_bit_exact(self):
        """forwardable llhist -> metricpb -> import decode recovers the
        registers bit-exactly."""
        from veneur_tpu.forward import llhistwire
        from veneur_tpu.forward.convert import (forwardable_to_protos,
                                                forwardable_to_wire)
        from veneur_tpu.forward.protos import metric_pb2

        store = _mk_store()
        _feed(store, [b"wire.k:%.4f|l|#env:t" % v
                      for v in np.random.default_rng(8).lognormal(2, 1, 300)])
        _, fwd = flush_columnstore(store, True, PCTS, AGGS)
        assert len(fwd.llhists) == 1
        meta, bins = fwd.llhists[0]
        protos = forwardable_to_protos(fwd)
        [pb] = [p for p in protos if p.WhichOneof("value") == "llhist"]
        assert pb.type == metric_pb2.LLHist
        rt = metric_pb2.Metric.FromString(pb.SerializeToString())
        assert np.array_equal(llhistwire.unmarshal(rt.llhist.bins), bins)
        # wire bytes match the proto serialization exactly
        assert pb.SerializeToString() in forwardable_to_wire(fwd)


class TestForwardTier:
    def test_global_percentile_bit_identical_to_single_node(self):
        """THE acceptance pin: two locals forward their bins; the global
        merge is bit-identical to a single-node llhist over the union
        stream — quantiles, counts, sums, buckets, everything."""
        from veneur_tpu.forward import server as fsrv
        from veneur_tpu.forward.convert import forwardable_to_protos
        from veneur_tpu.forward.protos import metric_pb2

        rng = np.random.default_rng(9)
        samples = rng.lognormal(2, 1.2, 1000)
        line = b"fwd.lat:%.6f|l|#svc:api"
        single = _mk_store()
        _feed(single, [line % v for v in samples])
        want, _ = flush_columnstore(single, False, PCTS, AGGS)

        locals_ = [_mk_store(), _mk_store()]
        _feed(locals_[0], [line % v for v in samples[:500]])
        _feed(locals_[1], [line % v for v in samples[500:]])
        global_store = _mk_store()

        class _Srv:
            _ignored = []

            class _S:
                pass
        srv = _Srv()
        srv._server = _Srv._S()
        srv._server.store = global_store
        buf = fsrv._MergeBuffer(srv)
        for st in locals_:
            _, fwd = flush_columnstore(st, True, PCTS, AGGS)
            for pb in forwardable_to_protos(fwd):
                buf.add(metric_pb2.Metric.FromString(pb.SerializeToString()))
        buf.flush_all()
        got, _ = flush_columnstore(global_store, False, PCTS, AGGS)

        def key(mm):
            return (mm.name, tuple(sorted(mm.tags)), int(mm.type))

        want_map = {key(mm): mm.value for mm in want}
        got_map = {key(mm): mm.value for mm in got}
        assert want_map.keys() == got_map.keys()
        for k in want_map:  # BIT-identical, not approximately equal
            assert got_map[k] == want_map[k], k

    def test_forward_import_over_grpc(self):
        """Full-plane integration: ForwardClient -> ImportServer."""
        from veneur_tpu.config import Config
        from veneur_tpu.core.server import Server
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.forward.server import ImportServer

        cfg = Config()
        cfg.interval = 3600.0
        cfg.statsd_listen_addresses = []
        cfg.apply_defaults()
        global_server = Server(cfg)
        imp = ImportServer(global_server, "127.0.0.1:0")
        imp.start()
        client = ForwardClient(imp.address, deadline=10.0)
        try:
            local = _mk_store()
            _feed(local, [b"grpc.lat:%.4f|l" % v for v in
                          np.random.default_rng(10).lognormal(1, 1, 200)])
            _, fwd = flush_columnstore(local, True, PCTS, AGGS)
            bins_sent = fwd.llhists[0][1].copy()
            assert client.forward(fwd) > 0
            table = global_server.store.llhists
            out, bins, touched, meta = table.snapshot_and_reset(PCTS)
            assert bins.shape[0] == 1
            assert np.array_equal(bins[0], bins_sent)
        finally:
            client.close()
            imp.stop()
            global_server.shutdown()


class TestTableBatchPath:
    def test_add_batch_matches_per_sample_add(self):
        """The columnar entry point (pre-interned rows, raw values,
        1/sample_rate weights) must land the same registers as the
        per-sample add path."""
        rng = np.random.default_rng(13)
        vals = rng.lognormal(1, 1, 600)
        rates = rng.choice([1.0, 0.5, 0.1], 600)
        s_batch, s_single = _mk_store(), _mk_store()
        p = Parser()
        stub = []
        p.parse_metric_fast(b"ab.k:1|l", stub.append)
        row_b = s_batch.llhists.intern(stub[0])
        s_batch.llhists.add_batch(
            np.full(600, row_b, np.int32), vals, 1.0 / rates)
        s_batch.llhists.apply_pending()
        from veneur_tpu.samplers.metrics import UDPMetric
        mm = stub[0]
        for v, r in zip(vals, rates):
            s_single.llhists.add(UDPMetric(
                key=mm.key, digest=mm.digest, digest64=mm.digest64,
                value=float(v), sample_rate=float(r), tags=mm.tags,
                scope=mm.scope))
        s_single.llhists.apply_pending()
        _, bins_b, _, _ = s_batch.llhists.snapshot_and_reset(PCTS)
        _, bins_s, _, _ = s_single.llhists.snapshot_and_reset(PCTS)
        assert np.array_equal(bins_b[0], bins_s[0])
        assert s_batch.llhists.samples_total == \
            s_single.llhists.samples_total


class TestEncodingSwitch:
    def test_parser_l_type(self):
        p = Parser()
        got = []
        p.parse_metric_fast(b"enc.x:1.5:2.5|l|#a:b", got.append)
        assert [mm.key.type for mm in got] == [m.LLHIST, m.LLHIST]
        assert [mm.value for mm in got] == [1.5, 2.5]

    def test_circllhist_encoding_routes_histograms(self):
        store = _mk_store(histogram_encoding="circllhist")
        _feed(store, [b"enc.t:12.5|ms", b"enc.h:3.5|h", b"enc.l:1|l"])
        assert len(store.llhists.rows) == 3
        assert len(store.histos.rows) == 0

    def test_tdigest_encoding_keeps_histograms(self):
        store = _mk_store()
        _feed(store, [b"enc.t:12.5|ms", b"enc.l:1|l"])
        assert len(store.histos.rows) == 1
        assert len(store.llhists.rows) == 1

    def test_unknown_encoding_rejected(self):
        with pytest.raises(ValueError):
            _mk_store(histogram_encoding="sparkline")


class TestFlushEmission:
    def test_buckets_cumulative_with_inf(self):
        store = _mk_store()
        _feed(store, [b"em.q:1.0:1.0:5.0:50.0|l|#env:t"])
        final, _ = flush_columnstore(store, False, PCTS, AGGS)
        buckets = [mm for mm in final if mm.name == "em.q.bucket"]
        assert buckets, [mm.name for mm in final]
        assert all(mm.type == MetricType.COUNTER for mm in buckets)
        vals = [mm.value for mm in buckets]
        assert vals == sorted(vals)  # cumulative over ascending le
        inf = [mm for mm in buckets if "le:+Inf" in mm.tags]
        assert len(inf) == 1 and inf[0].value == 4.0
        count = [mm for mm in final if mm.name == "em.q.count"]
        assert count[0].value == 4.0
        assert count[0].type == MetricType.COUNTER

    def test_local_mixed_forwards_not_emits(self):
        store = _mk_store()
        _feed(store, [b"fw.q:3.5|l"])
        final, fwd = flush_columnstore(store, True, PCTS, AGGS)
        assert not [mm for mm in final if mm.name.startswith("fw.q")]
        assert len(fwd.llhists) == 1

    def test_local_only_rows_flush_locally(self):
        store = _mk_store()
        _feed(store, [b"lo.q:3.5|l|#veneurlocalonly"])
        final, fwd = flush_columnstore(store, True, PCTS, AGGS)
        assert [mm for mm in final if mm.name == "lo.q.count"]
        assert not fwd.llhists

    def test_batch_path_parity(self):
        lines = [b"par.q:%.4f|l|#env:t" % v for v in
                 np.random.default_rng(11).lognormal(1, 1, 400)]
        lines += [b"par.local:2.5|l|#veneurlocalonly",
                  b"par.glob:9.5|l|#veneurglobalonly"]
        for is_local in (False, True):
            s1, s2 = _mk_store(), _mk_store()
            _feed(s1, lines)
            _feed(s2, lines)
            final, fwd1 = flush_columnstore(s1, is_local, PCTS, AGGS)
            batch, fwd2 = flush_columnstore_batch(s2, is_local, PCTS, AGGS)

            def key(mm):
                return (mm.name, round(float(mm.value), 6),
                        tuple(sorted(mm.tags)), int(mm.type))
            assert sorted(map(key, batch.materialize())) == \
                sorted(map(key, final))
            assert len(fwd1.llhists) == len(fwd2.llhists)
            for (m1, b1), (m2, b2) in zip(
                    sorted(fwd1.llhists, key=lambda e: e[0].name),
                    sorted(fwd2.llhists, key=lambda e: e[0].name)):
                assert m1.name == m2.name
                assert np.array_equal(b1, b2)


class TestCarryover:
    def test_merge_forwardable_llhists_sum(self):
        from veneur_tpu.core.columnstore import RowMeta
        from veneur_tpu.samplers.metrics import MetricScope
        from veneur_tpu.util.resilience import merge_forwardable

        def meta(name):
            return RowMeta(name=name, tags=[], joined_tags="", digest32=1,
                           scope=MetricScope.MIXED, wire_type=m.LLHIST)

        a = np.zeros(llhist_ref.BINS, np.int64)
        b = np.zeros(llhist_ref.BINS, np.int64)
        a[10], b[10], b[20] = 5, 7, 3
        newer = ForwardableState(llhists=[(meta("x"), a)])
        older = ForwardableState(llhists=[(meta("x"), b),
                                          (meta("y"), b.copy())])
        merged = merge_forwardable(newer, older)
        by_name = {mm.name: bins for mm, bins in merged.llhists}
        assert by_name["x"][10] == 12 and by_name["x"][20] == 3
        assert by_name["y"][10] == 7

    @pytest.mark.chaos
    def test_carryover_register_sum_lossless_under_chaos(self):
        """PR-2 chaos soak, llhist edition: rounds of forwarding with a
        30% injected fault rate deliver exactly the register sums a
        fault-free run delivers — nothing lost, nothing double-counted."""
        from veneur_tpu.forward.client import ForwardClient
        from veneur_tpu.testing.forwardtest import ForwardTestServer
        from veneur_tpu.util import chaos as chaos_mod
        from veneur_tpu.util.chaos import Chaos
        from veneur_tpu.forward import llhistwire

        def run_rounds(error_rate, rounds=8, seed=12):
            received = []
            ft = ForwardTestServer(received.extend)
            ft.start()
            chaos = (Chaos(error_rate=error_rate,
                           seams=("forward_send",), seed=seed)
                     if error_rate else None)
            client = ForwardClient(ft.address, deadline=5.0, chaos=chaos)
            client.retry.max_attempts = 1  # carryover alone must carry
            client.carryover.max_intervals = 1000
            client.breaker.failure_threshold = 10_000
            rng = np.random.default_rng(seed)
            sent = np.zeros(llhist_ref.BINS, np.int64)
            try:
                store = _mk_store()
                for i in range(rounds):
                    _feed(store, [b"soak.lat:%.4f|l" % v
                                  for v in rng.lognormal(1, 1, 50)])
                    _, fwd = flush_columnstore(store, True, PCTS, AGGS)
                    sent += fwd.llhists[0][1]
                    client.forward(fwd)
                if chaos is not None:
                    chaos.enabled = False
                # clean drain flush for any pending carryover
                client.forward(ForwardableState())
                assert client.carryover.depth == 0
                got = np.zeros(llhist_ref.BINS, np.int64)
                for pb in received:
                    if pb.WhichOneof("value") == "llhist":
                        got += llhistwire.unmarshal(pb.llhist.bins)
                return got, sent
            finally:
                client.close()
                ft.stop()

        got_chaos, sent_chaos = run_rounds(0.3)
        got_clean, sent_clean = run_rounds(0.0)
        assert np.array_equal(sent_chaos, sent_clean)
        assert np.array_equal(got_clean, sent_clean)  # control
        assert np.array_equal(got_chaos, sent_chaos)  # zero loss
