# veneur-tpu container image (reference Dockerfile parity): the server
# plus all four console scripts. g++ stays in the image because the
# native ingest hot path (veneur_tpu/native/dogstatsd.cc) compiles on
# first use and falls back to pure Python without it.
FROM python:3.12-slim

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/veneur-tpu
COPY pyproject.toml README.md ./
COPY veneur_tpu ./veneur_tpu
RUN pip install --no-cache-dir .[sinks]

# pre-compile the native parser so first packet doesn't pay the build
RUN python -c "from veneur_tpu import native; assert native.available(), \
    native.unavailable_reason()"

COPY examples ./examples

# DogStatsD UDP, HTTP API, SSF UDP (match examples/example.yaml)
EXPOSE 8126/udp 8127/tcp 8128/udp

ENTRYPOINT ["veneur-tpu"]
CMD ["-f", "examples/example.yaml"]
